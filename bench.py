"""Benchmark: RS(10,4) EC encode, TPU vs native CPU (BASELINE.md).

The HEADLINE (value/vs_baseline of the one JSON line) is the defensible
like-for-like comparison for the run's conditions — normally
`device_kernel_chained`: the chained-slope device kernel rate (>=3
chain lengths of serially-dependent encodes in one dispatch,
least-squares slope with R^2/deviation diagnostics — tunnel-RTT-free
by construction) against the native CPU in-memory encode. The
tunnel-bounded e2e run (disk + h2d + MXU + d2h + shard writes, all 14
shard files sha256-compared against the CPU path) reports as annotated
context under "e2e_tunnel" — on this sandbox it saturates the shared
axon link (e2e_vs_link_bound=1.0), which is an environmental bound,
not a kernel result. Fallback headlines are explicitly marked
(headline_kind: cpu_e2e_device_unreachable / ..._failed_midrun /
tpu_e2e_tunnel_bound). Pass --require-tpu to turn every CPU-fallback
headline into a hard failure (exit 2) — for perf gates that must never
record a CPU number as the run's result.

Prints ONE JSON line:
  {"metric": "ec_encode_rs10_4_mbps", "value": <MB/s>, "unit": "MB/s",
   "vs_baseline": <value / cpu denominator>, "headline_kind": ...}

Env knobs: SW_BENCH_DAT_MB (volume size, default 4096),
SW_BENCH_SLAB_MB (device slab per shard row, default 8),
SW_BENCH_TRIALS (best-of trials per timed pass, default 2),
SW_BENCH_INIT_TIMEOUT (default 180s), SW_BENCH_DIR (workdir).
BASELINE configs 3-5 scale via SW_BENCH_GEO_MB (RS(6,3)/RS(20,4)
volume size, default 256; device figures are chained-slope too),
SW_BENCH_SMALL_VOLS/SW_BENCH_SMALL_NEEDLES (batched 4KB-needle
volumes, default 4 x 8192), SW_BENCH_CLUSTER_MB/
SW_BENCH_CLUSTER_SERVERS (live-cluster ec.rebuild with the MESH
backend: always on an 8-device virtual CPU mesh in a subprocess, plus
the live chip when reachable; gather/compute phase fractions
reported).
"""

import hashlib
import json
import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from seaweedfs_tpu.util import config  # noqa: E402

K, M = 10, 4
TOTAL = K + M
TRIALS = config.env_int("SW_BENCH_TRIALS")


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def generate_dat(path: str, size_mb: int) -> int:
    """Write size_mb MB of deterministic pseudo-random bytes, streamed."""
    rng = np.random.default_rng(0)
    chunk = 128 << 20
    total = size_mb << 20
    t = time.perf_counter()
    with open(path, "wb") as f:
        written = 0
        while written < total:
            n = min(chunk, total - written)
            f.write(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
            written += n
    log(f"generated {size_mb}MB .dat in {time.perf_counter() - t:.1f}s")
    return total


def shard_digests(base: str) -> list:
    from seaweedfs_tpu.ec import to_ext
    from seaweedfs_tpu.util import file_sha256
    out = []
    for i in range(TOTAL):
        with open(base + to_ext(i), "rb") as f:
            out.append(file_sha256(f))
    return out


def remove_shards(base: str, ids=range(TOTAL)):
    from seaweedfs_tpu.ec import to_ext
    for i in ids:
        p = base + to_ext(i)
        if os.path.exists(p):
            os.remove(p)


def ensure_native():
    """Build (or rebuild) the native lib; a stale pre-threading .so would
    silently give a single-threaded denominator."""
    import seaweedfs_tpu.ops.rs_native as rs_native

    def has_mt():
        lib = rs_native._load()
        return lib is not None and hasattr(lib, "sw_ec_matmul_mt")

    if not has_mt():
        import importlib
        import subprocess
        subprocess.run([os.path.join(os.path.dirname(__file__),
                                     "seaweedfs_tpu/ops/native/build.sh")],
                       check=False, capture_output=True)
        rs_native = importlib.reload(rs_native)
    return rs_native.native_available()


def measure_cpu_e2e(base: str, dat_size: int) -> float:
    """End-to-end native encode. Slab 1MB: the native path is fastest when
    rows fit in LLC (the reference streams 256KB buffers for the same
    reason), so the denominator gets its best configuration."""
    from seaweedfs_tpu.ec import write_ec_files
    from seaweedfs_tpu.ops.codec import get_codec
    backend = "native" if ensure_native() else "numpy"
    codec = get_codec(K, M, backend=backend)  # native: all hw threads
    best = 0.0
    for trial in range(TRIALS):
        os.sync()  # settle writeback so each trial starts clean
        t = time.perf_counter()
        write_ec_files(base, codec=codec, slab=1 << 20, pipelined=False)
        dt = time.perf_counter() - t
        best = max(best, dat_size / dt / 1e6)
        log(f"cpu[{backend}] e2e encode trial {trial}: "
            f"{dat_size / dt / 1e6:.0f} MB/s ({dt:.1f}s)")
    return best


def init_device(timeout_s: float):
    """Watchdogged first TPU touch; returns jax devices or None."""
    result = {}

    def probe():
        try:
            import jax
            from seaweedfs_tpu.util.jax_platform import (
                honor_platform_request)
            honor_platform_request()
            result["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001
            result["error"] = e

    th = threading.Thread(target=probe, daemon=True)
    th.start()
    th.join(timeout_s)
    if th.is_alive() or "devices" not in result:
        log(f"device init failed/hung ({result.get('error', 'timeout')})")
        return None
    return result["devices"]


def init_device_retrying(retry_log: list):
    """VERDICT r4 weak#3: one failed probe at minute 0 must not forfeit
    the round's device headline. Re-probes, each watchdogged; every
    attempt lands in the artifact so a still-down tunnel is provable
    rather than assumed.

    r05 burned ~15 min of wall on six fixed-interval timeouts before
    falling back — attempts are now capped by SW_BENCH_DEVICE_INIT_RETRIES
    and spaced with exponential backoff (base SW_BENCH_INIT_RETRY_SPACING,
    doubling up to SW_BENCH_INIT_RETRY_MAX_SPACING), and the CPU-fallback
    verdict is recorded in the log the moment the last probe fails."""
    attempts = max(1, config.env_int(
        "SW_BENCH_DEVICE_INIT_RETRIES",
        config.env_int("SW_BENCH_INIT_RETRIES")))
    timeout_s = config.env_float("SW_BENCH_INIT_RETRY_TIMEOUT")
    spacing_s = config.env_float("SW_BENCH_INIT_RETRY_SPACING")
    max_spacing_s = config.env_float("SW_BENCH_INIT_RETRY_MAX_SPACING")
    for i in range(attempts):
        t0 = time.time()
        log(f"device init retry {i + 1}/{attempts}")
        devices = init_device(timeout_s)
        retry_log.append({"attempt": len(retry_log) + 1,
                          "t_unix": round(t0),
                          "ok": devices is not None})
        if devices is not None:
            return devices
        if i < attempts - 1:
            backoff = min(spacing_s * (2 ** i), max_spacing_s)
            retry_log[-1]["backoff_s"] = round(backoff, 3)
            time.sleep(backoff)
    retry_log.append({"fallback": "cpu", "t_unix": round(time.time()),
                      "after_attempts": attempts})
    log(f"device init: still down after {attempts} capped attempts; "
        f"falling back to CPU now")
    return None


def probe_link():
    """Measure raw h2d/d2h of the host↔device link at bench time. The
    axon tunnel's bandwidth is shared and varies run to run (observed
    h2d 46MB/s..1.4GB/s, d2h 8..43MB/s); this records the conditions the
    e2e number was taken under so it can be interpreted. Returns
    (h2d, d2h) MB/s."""
    import jax.numpy as jnp
    a = np.zeros(32 << 20, dtype=np.uint8)
    t = time.perf_counter()
    dev = jnp.asarray(a)
    dev.block_until_ready()
    h2d = a.nbytes / (time.perf_counter() - t) / 1e6
    t = time.perf_counter()
    np.asarray(dev)
    d2h = a.nbytes / (time.perf_counter() - t) / 1e6
    log(f"link probe: h2d {h2d:.0f} MB/s, d2h {d2h:.0f} MB/s "
        f"(e2e TPU encode is bounded by ~min(h2d, d2h/0.4) payload MB/s)")
    return h2d, d2h


def measure_tpu_e2e(base: str, dat_size: int, slab_mb: int):
    """Returns (best MB/s, stage dict of the best trial). Each trial logs
    a per-stage breakdown (VERDICT r2 #2) and the pipeline efficiency
    against the link bound measured *inside* that trial (effective h2d /
    d2h rates over the stages' busy windows — the isolated probe is a
    different instant on a shared tunnel)."""
    from seaweedfs_tpu.ec import write_ec_files
    from seaweedfs_tpu.ops.rs_tpu import TpuCodec
    from seaweedfs_tpu.util.profiling import StageTimer, maybe_trace
    codec = TpuCodec(K, M)
    # warm the compile cache for every power-of-two bucket the coalesced
    # stream can hit (steady-state batches are exactly slab wide; the tail
    # batch is a smaller multiple of the 1MB small block) so no JIT
    # compile lands inside the timed region
    from seaweedfs_tpu.ops.pipeline import PipelinedMatmul
    warm = PipelinedMatmul(codec.matrix[K:], max_width=slab_mb << 20)
    widths, w = [], slab_mb << 20
    while w >= 1 << 20:
        widths.append(w)
        w >>= 1
    list(warm.stream(iter(
        [(0, np.zeros((K, wi), dtype=np.uint8)) for wi in widths])))
    best, best_stages = 0.0, {}
    for trial in range(TRIALS):
        os.sync()  # settle prior-pass writeback so timing starts clean
        timer = StageTimer()
        t = time.perf_counter()
        with maybe_trace(f"tpu_e2e_encode_t{trial}"):
            write_ec_files(base, codec=codec, slab=slab_mb << 20,
                           pipelined=True, timer=timer)
        dt = time.perf_counter() - t
        mbps = dat_size / dt / 1e6
        log(f"tpu e2e encode trial {trial} (disk+h2d+mxu+d2h+write): "
            f"{mbps:.0f} MB/s ({dt:.1f}s, "
            f"{slab_mb}MB coalesced batches per device call)")
        log(f"  stages: {timer.summary()}")
        h2d_eff = timer.rate_mbps("h2d", use_busy=True)
        d2h_eff = timer.rate_mbps("d2h+mxu", use_busy=True)
        stages = {
            "h2d_eff_mbps": round(h2d_eff, 1),
            "d2h_eff_mbps": round(d2h_eff, 1),
            "d2h_busy_frac": round(timer.busy_time("d2h+mxu") / dt, 2),
            "disk_read_mbps": round(timer.rate_mbps("disk_read", True), 1),
            "shard_write_mbps": round(
                timer.rate_mbps("shard_write", True), 1),
        }
        if h2d_eff and d2h_eff:
            bound = min(h2d_eff, d2h_eff / (M / K))
            stages["in_run_link_bound_mbps"] = round(bound, 1)
            stages["e2e_vs_link_bound"] = round(mbps / bound, 2)
            log(f"  in-run link bound min(h2d, d2h/{M / K}) = "
                f"{bound:.0f} MB/s -> e2e at {mbps / bound:.0%} of bound")
        if mbps > best:
            best, best_stages = mbps, stages
    return best, best_stages


def _measure_rebuild(base: str, dat_size: int, codec, label: str,
                     seed: int, slab: int, pipelined: bool) -> float:
    """Shared BASELINE-config-2 harness: drop M seeded-random shards,
    rebuild with the given codec, digest-verify, report MB/s of volume
    bytes."""
    import random
    from seaweedfs_tpu.ec import rebuild_ec_files
    before = shard_digests(base)
    dropped = sorted(random.Random(seed).sample(range(TOTAL), M))
    remove_shards(base, dropped)
    t = time.perf_counter()
    rebuilt = rebuild_ec_files(base, codec=codec, slab=slab,
                               pipelined=pipelined)
    dt = time.perf_counter() - t
    assert sorted(rebuilt) == dropped, (rebuilt, dropped)
    if shard_digests(base) != before:
        raise AssertionError(
            f"{label} rebuild of shards {dropped} not byte-identical")
    mbps = dat_size / dt / 1e6
    log(f"{label} e2e rebuild of {M} shards: {mbps:.0f} MB/s of volume "
        f"bytes ({dt:.1f}s, dropped {dropped}, digests verified)")
    return mbps


def measure_tpu_rebuild(base: str, dat_size: int, slab_mb: int):
    """Drop 4 random shards, rebuild through the device, verify digests."""
    from seaweedfs_tpu.ops.rs_tpu import TpuCodec
    return _measure_rebuild(base, dat_size, TpuCodec(K, M), "tpu",
                            seed=42, slab=slab_mb << 20, pipelined=True)


def measure_cpu_rebuild(base: str, dat_size: int) -> float:
    """BASELINE config 2 on the CPU path: drop M random shards of the
    just-encoded volume, rebuild with the native codec, verify digests.
    Runs in every mode so the fallback artifact still carries a
    rebuild number (device runs add the TPU variant on top)."""
    from seaweedfs_tpu.ops.codec import get_codec
    backend = "native" if ensure_native() else "numpy"
    return _measure_rebuild(base, dat_size,
                            get_codec(K, M, backend=backend),
                            f"cpu[{backend}]", seed=7, slab=1 << 20,
                            pipelined=False)


def measure_cpu_inmem(slab_mb: int, iters: int = 6) -> float:
    """Like-for-like denominator for the device-resident figure: the
    native AVX2-style codec on in-memory buffers, no file I/O."""
    from seaweedfs_tpu.ops.codec import get_codec
    if not ensure_native():
        return 0.0
    codec = get_codec(K, M, backend="native")
    n = slab_mb << 20
    rng = np.random.default_rng(2)
    bufs = [rng.integers(0, 256, (K, n), dtype=np.uint8) for _ in range(3)]
    codec.encode(bufs[0])  # warm threads
    times = []
    for i in range(iters):
        t = time.perf_counter()
        codec.encode(bufs[i % len(bufs)])
        times.append(time.perf_counter() - t)
    best = (K * n) / min(times) / 1e6
    log(f"cpu[native] in-memory encode (no I/O): best {best:.0f} MB/s")
    return best


def measure_device_resident(slab_mb: int, iters: int = 8):
    """Honest device-resident figure: per-iteration sync, rotating fresh
    buffers so no result can be served from an unexecuted cached launch.
    Returns (median, best, pipelined) MB/s."""
    import jax.numpy as jnp
    from seaweedfs_tpu.ops.rs_tpu import make_encode_fn
    n = slab_mb << 20
    fn, bitmat = make_encode_fn(K, M, n)
    bm = jnp.asarray(bitmat)
    rng = np.random.default_rng(1)
    bufs = [jnp.asarray(rng.integers(0, 256, (K, n), dtype=np.uint8))
            for _ in range(3)]
    for b in bufs:
        b.block_until_ready()
    fn(bm, bufs[0]).block_until_ready()  # compile
    times = []
    for i in range(iters):
        t = time.perf_counter()
        fn(bm, bufs[i % len(bufs)]).block_until_ready()
        times.append(time.perf_counter() - t)
    best = (K * n) / min(times) / 1e6
    med = (K * n) / sorted(times)[len(times) // 2] / 1e6
    log(f"tpu device-resident encode (per-iter sync, rotating buffers): "
        f"median {med:.0f} MB/s, best {best:.0f} MB/s")
    # throughput view: dispatch all, sync once — still honest (distinct
    # rotating inputs, every dispatched executable runs) but without a
    # host round-trip per iteration, which dominates over a remote link
    t = time.perf_counter()
    outs = [fn(bm, bufs[i % len(bufs)]) for i in range(iters)]
    for o in outs:
        o.block_until_ready()
    thr = (K * n * iters) / (time.perf_counter() - t) / 1e6
    log(f"tpu device-resident encode (pipelined dispatch, one sync): "
        f"{thr:.0f} MB/s")
    return med, best, thr


def measure_device_chained(slab_mb: int, k: int = K, m: int = M,
                           lens=(5, 15, 25), min_r2: float = 0.98):
    """Tunnel-independent kernel figure: run N serially-dependent encodes
    inside ONE dispatch (each iteration xors its parity back into the
    payload, so no iteration can be elided or reordered), timed at >= 3
    chain lengths; the least-squares slope cancels the fixed
    dispatch/RTT cost that dominates per-call timing over the remote
    axon link (~65ms/call). Every byte of every extra iteration is real
    serialized device work, so the slope is an honest steady-state
    compute rate — and the R^2 / max-deviation diagnostics pin that the
    three points actually lie on a line (one tunnel hiccup landing on a
    single point would otherwise skew a two-point subtraction
    silently; VERDICT r3 weak#3).

    Returns (rate_mbps, fit_diagnostics)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from seaweedfs_tpu.ops.rs_tpu import make_encode_fn
    n = slab_mb << 20
    fn, bitmat = make_encode_fn(k, m, n)
    bm = jnp.asarray(bitmat)

    def make(iters):
        @jax.jit
        def chained(bm, x0):
            def body(_, x):
                y = fn(bm, x)
                return x.at[:m, :].set(x[:m, :] ^ y)
            return lax.fori_loop(0, iters, body, x0)[0, 0]
        return chained

    # distinct input per timed call: repeating an identical (fn, value)
    # call over the axon relay has been observed to return anomalously
    # fast (result served without re-execution), which would corrupt the
    # slope — rotating fresh buffers defeats any such value-level caching
    xs = [jax.random.randint(jax.random.PRNGKey(i), (k, n), 0, 256,
                             dtype=jnp.int32).astype(jnp.uint8)
          for i in range(4)]
    for x in xs:
        x.block_until_ready()

    def best_time(iters, reps=3):
        ch = make(iters)
        int(ch(bm, xs[3]))   # compile + materialize
        ts = []
        for i in range(reps):
            t = time.perf_counter()
            # int() fetches the scalar to the host: over the axon relay,
            # block_until_ready alone can return at dispatch-ack, before
            # the chain has actually executed — a host fetch cannot
            int(ch(bm, xs[i % 3]))
            ts.append(time.perf_counter() - t)
        return min(ts)

    def fit():
        times = [best_time(it) for it in lens]
        its = np.asarray(lens, dtype=np.float64)
        ts = np.asarray(times, dtype=np.float64)
        slope, intercept = np.polyfit(its, ts, 1)
        pred = slope * its + intercept
        ss_res = float(((ts - pred) ** 2).sum())
        ss_tot = float(((ts - ts.mean()) ** 2).sum()) or 1e-12
        r2 = 1.0 - ss_res / ss_tot
        max_dev = float(np.abs(ts - pred).max() / ts.mean())
        return slope, times, r2, max_dev

    slope, times, r2, max_dev = fit()
    if slope <= 0 or r2 < min_r2:   # tunnel hiccup: one retry
        log(f"chained fit noisy (slope {slope:.4g}, r2 {r2:.3f}); "
            f"retrying")
        slope, times, r2, max_dev = fit()
    if slope <= 0 or r2 < min_r2:
        raise RuntimeError(
            f"chained timings not linear in chain length: "
            f"lens {list(lens)} -> {[round(t, 4) for t in times]} "
            f"(slope {slope:.4g}, r2 {r2:.3f})")
    rate = k * n / slope
    diag = {"chain_lens": list(lens),
            "times_s": [round(t, 4) for t in times],
            "r2": round(r2, 4), "max_dev_frac": round(max_dev, 3)}
    log(f"tpu chained-slope rs({k},{m}) encode ({list(lens)} serial "
        f"iters, {slab_mb}MB slab): {rate / 1e9:.1f} GB/s payload "
        f"(r2 {r2:.4f}, max dev {max_dev:.1%})")
    return rate / 1e6, diag


def measure_geometries(size_mb: int, chained_by_geo: dict = None) -> dict:
    """BASELINE config 4: RS(6,3) and RS(20,4) — correctness is pinned by
    tests/test_rs_codec.py; this measures MB/s on the native backend
    (e2e encode of a real .dat). The device figure per geometry is the
    CHAINED-SLOPE kernel rate measured pre-e2e on a quiet device and
    injected here (`chained_by_geo`) — the per-call numbers previously
    reported were RTT-dominated tunnel artifacts, comparable to nothing
    (VERDICT r3 weak#4)."""
    import shutil as _shutil
    from seaweedfs_tpu.ec import write_ec_files
    from seaweedfs_tpu.ops.codec import get_codec
    out = {}
    for k, m in ((6, 3), (20, 4)):
        gdir = tempfile.mkdtemp(prefix=f"swgeo_{k}_{m}_")
        base = os.path.join(gdir, "1")
        try:
            size = generate_dat(base + ".dat", size_mb)
            codec = get_codec(k, m, backend="native"
                              if ensure_native() else "numpy")
            t = time.perf_counter()
            write_ec_files(base, codec=codec, slab=1 << 20,
                           pipelined=False)
            native_mbps = size / (time.perf_counter() - t) / 1e6
            entry = {"native_e2e_mbps": round(native_mbps)}
            chained = (chained_by_geo or {}).get((k, m))
            if chained:
                rate, diag = chained
                entry["device_chained_mbps"] = round(rate)
                entry["chained_fit"] = diag
            out[f"rs_{k}_{m}"] = entry
            log(f"rs({k},{m}) on {size_mb}MB: {entry}")
        finally:
            _shutil.rmtree(gdir, ignore_errors=True)
    return out


def measure_batched_small_needles(n_volumes: int = 4,
                                  needles_per_volume: int = 8192) -> dict:
    """BASELINE config 3 (scaled): volumes full of 4KB needles encoded
    through the coalesced-batch streaming path. The full 1M x 4KB x 32
    volumes run is the same code at bigger constants (env-scalable via
    SW_BENCH_SMALL_VOLS / SW_BENCH_SMALL_NEEDLES)."""
    import shutil as _shutil
    from seaweedfs_tpu.ec import write_ec_files
    from seaweedfs_tpu.ops.codec import get_codec
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume
    workdir = tempfile.mkdtemp(prefix="swsmall_")
    try:
        rng = np.random.default_rng(9)
        payload = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
        total_bytes = 0
        t_build = time.perf_counter()
        for vi in range(n_volumes):
            v = Volume(workdir, "", vi + 1, create=True)
            for i in range(1, needles_per_volume + 1):
                v.write_needle(Needle(id=i, cookie=1, data=payload))
            total_bytes += v.size()
            v.close()
        build_s = time.perf_counter() - t_build
        codec = get_codec(K, M, backend="native"
                          if ensure_native() else "numpy")
        t = time.perf_counter()
        for vi in range(n_volumes):
            write_ec_files(os.path.join(workdir, str(vi + 1)),
                           codec=codec, slab=1 << 20, pipelined=False)
        dt = time.perf_counter() - t
        mbps = total_bytes / dt / 1e6
        log(f"batched small-needle encode: {n_volumes} volumes x "
            f"{needles_per_volume} x 4KB = {total_bytes / 1e6:.0f} MB, "
            f"{mbps:.0f} MB/s (write {build_s:.1f}s, encode {dt:.1f}s)")
        return {"volumes": n_volumes, "needles_per_volume":
                needles_per_volume, "total_mb": round(total_bytes / 1e6),
                "encode_mbps": round(mbps)}
    finally:
        _shutil.rmtree(workdir, ignore_errors=True)


def _cluster_holder_health(master_url: str) -> dict:
    """Per-holder {holder: score} from the master's /cluster/health
    fold (forcing a scrape so the drill's fetches are in the EWMAs);
    empty on any failure — health reporting must never fail a bench."""
    from seaweedfs_tpu.server.http_util import get_json
    try:
        view = get_json(f"http://{master_url}/cluster/health?refresh=1")
        return {holder: h.get("score")
                for holder, h in (view.get("holders") or {}).items()}
    except Exception:  # noqa: BLE001
        return {}


def measure_cluster_rebuild(size_mb: int = 256, n_servers: int = 4,
                            backend: str = None) -> dict:
    """BASELINE config 5 (scaled): EC volume spread over a live cluster,
    shards on one server destroyed, rebuilt on another — the parallel
    survivor gather, the GF rebuild compute and the mount are timed as
    phases (via do_ec_rebuild's timings hook) so the network/compute
    split is reported, not guessed. Backend for the rebuild compute:
    SW_BENCH_CLUSTER_BACKEND or the `backend` arg (default mesh — the
    device-mesh serving path; the driver's virtual-CPU-mesh run goes
    through run_cluster_drill_subprocess)."""
    import shutil as _shutil
    from seaweedfs_tpu.client import operation as op
    from seaweedfs_tpu.server.http_util import get_json, post_json
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    backend = backend or config.env_str("SW_BENCH_CLUSTER_BACKEND")
    workdir = tempfile.mkdtemp(prefix="swcluster_")
    master = MasterServer(port=0, volume_size_limit_mb=size_mb * 2,
                          pulse_seconds=1).start()
    servers = []
    try:
        for i in range(n_servers):
            servers.append(VolumeServer(
                port=0, directories=[os.path.join(workdir, f"v{i}")],
                master_url=master.url, pulse_seconds=1,
                max_volume_counts=[10], ec_backend=backend).start())
        # one volume filled with data
        a = op.assign(master.url, collection="bench")
        vid = int(a["fid"].split(",")[0])
        rng = np.random.default_rng(4)
        chunk = rng.integers(0, 256, 4 << 20, dtype=np.uint8).tobytes()
        written = 0
        i = 0
        while written < (size_mb << 20):
            i += 1
            op.upload(a["url"], f"{vid},{i:x}00000001", chunk,
                      filename=f"b{i}")
            written += len(chunk)
        # encode + spread via the shell orchestration
        import seaweedfs_tpu.shell  # noqa: F401
        from seaweedfs_tpu.shell.command_env import CommandEnv, run_command
        # shell progress to stderr: stdout carries ONLY the bench JSON
        env = CommandEnv(master.url, out=sys.stderr)
        # keep the drill bounded even if the device link degrades
        # mid-run (the interactive shell default is a generous 3600s;
        # a wedged tunnel would stall the whole bench on it)
        env.admin_timeout = config.env_float("SW_BENCH_DRILL_TIMEOUT")
        from seaweedfs_tpu.shell.command_ec import do_ec_encode
        # device-runtime bracketing: every drill server runs in-process,
        # so the process-global DEVICE_STATS sees the rebuilder's
        # compiles directly. The deltas split XLA compile wall out of
        # each phase headline and gate recompiles == 0 after warmup.
        from seaweedfs_tpu.ops import device_stats as _dstats
        dsnap0 = _dstats.DEVICE_STATS.snapshot()
        enc_timings = {}
        t_encode = time.perf_counter()
        do_ec_encode(env, vid, timings=enc_timings)
        encode_s = time.perf_counter() - t_encode
        enc_dev = _dstats.delta(dsnap0)
        dsnap1 = _dstats.DEVICE_STATS.snapshot()

        # shard ownership reaches the master via the store-change
        # immediate push; poll with a deadline instead of sleeping a
        # pulse (VERDICT r4 weak#4: fixed sleeps race on loaded hosts)
        def poll(pred, what, timeout=30.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    got = pred()
                except Exception:  # noqa: BLE001 - master mid-update
                    got = None
                if got is not None:
                    return got
                time.sleep(0.1)
            raise TimeoutError(f"cluster drill: {what} not observed "
                               f"within {timeout}s")

        def lookup_shards():
            out = get_json(f"http://{master.url}/cluster/ec_lookup"
                           f"?volumeId={vid}")
            return {int(s): urls for s, urls in out["shards"].items()}

        ec = {"shards": poll(
            lambda: (lambda m: m if set(m) == set(range(TOTAL))
                     else None)(lookup_shards()),
            "all 14 encoded shards at the master")}
        by_holder = {}
        for sid, urls in ec["shards"].items():
            for u in urls:
                by_holder.setdefault(u, []).append(int(sid))
        victim, lost = max(by_holder.items(), key=lambda kv: len(kv[1]))
        # cap the destroyed set at the parity count — losing more than M
        # shards is unrecoverable by construction (RS(10,4)), and a
        # small server count concentrates >M shards per holder
        lost = sorted(lost)[:M]
        post_json(f"http://{victim}/admin/ec/unmount?volume={vid}"
                  f"&shards={','.join(map(str, sorted(lost)))}")
        post_json(f"http://{victim}/admin/ec/delete_shards?volume={vid}"
                  f"&collection=bench"
                  f"&shards={','.join(map(str, sorted(lost)))}")
        # loss visible at the master (immediate push again) before the
        # rebuilder plans which shards to regenerate
        shard_map = poll(
            lambda: (lambda m: m if not any(
                victim in m.get(s, []) for s in lost) else None)(
                lookup_shards()),
            "shard loss at the master")
        # rebuild (shell picks the rebuilder, pulls survivors in
        # parallel, runs the GF rebuild) — phase-timed
        from seaweedfs_tpu.shell.command_ec import do_ec_rebuild
        missing = [s for s in range(TOTAL) if s not in shard_map]
        timings = {}
        t_rebuild = time.perf_counter()
        do_ec_rebuild(env, vid, "bench", shard_map, missing,
                      timings=timings)
        rebuild_s = time.perf_counter() - t_rebuild
        reb_dev = _dstats.delta(dsnap1)
        dsnap2 = _dstats.DEVICE_STATS.snapshot()
        ec2 = get_json(f"http://{master.url}/cluster/ec_lookup"
                       f"?volumeId={vid}")
        have = {int(s) for s in ec2["shards"]}
        ok = have == set(range(TOTAL))
        gather_s = timings.get("gather_s", 0.0)
        compute_s = timings.get("compute_s", 0.0)
        # device telemetry relayed from the rebuilder (rebuild_ec_files
        # via /admin/ec/rebuild): dispatch discipline must be VISIBLE in
        # vs_baseline — a regression back to per-slab bitmat uploads or
        # two-dispatch slabs shows here before it shows in wall time
        stream_s = timings.get("stream_s", 0.0)
        survivor_bytes = timings.get("survivor_bytes", 0)
        # mesh-sharded dispatch width: recompute from the per-device
        # byte map (survives _merge_rebuild_stats' dict overwrite
        # semantics) with the rebuilder's derived value as fallback —
        # width 1 here means the codec fell back to a single device and
        # the "one dispatch drives all devices" property regressed
        mesh_bytes = {d: b for d, b in
                      (timings.get("mesh_device_bytes") or {}).items()
                      if b}
        if mesh_bytes:
            peak = max(mesh_bytes.values())
            width_devices = len(mesh_bytes)
            busy_frac = {d: round(b / peak, 3)
                         for d, b in sorted(mesh_bytes.items())}
        else:
            width_devices = timings.get("dispatch_width_devices", 0)
            busy_frac = timings.get("device_busy_frac", {})

        # -- single-shard repair drill: the overwhelmingly common
        # failure at fleet scale. Destroy exactly ONE shard and rebuild
        # with -repair auto — the trace path ships projected sub-shard
        # symbols from all survivors, so repair_bytes_frac must land
        # well under 1.0 (the k*shard full-gather baseline).
        shard_map2 = poll(
            lambda: (lambda m: m if set(m) == set(range(TOTAL))
                     else None)(lookup_shards()),
            "all shards back at the master before the repair drill")
        lone_sid = sorted(shard_map2)[0]
        lone_holder = shard_map2[lone_sid][0]
        post_json(f"http://{lone_holder}/admin/ec/unmount?volume={vid}"
                  f"&shards={lone_sid}")
        post_json(f"http://{lone_holder}/admin/ec/delete_shards"
                  f"?volume={vid}&collection=bench&shards={lone_sid}")
        # a lone-held shard vanishes from the lookup map entirely once
        # its only holder drops it (lookup_ec_shards omits empty holder
        # lists), so "key absent" IS the loss signal — a [lone_holder]
        # default here would wait forever
        shard_map2 = poll(
            lambda: (lambda m: m if lone_holder not in
                     m.get(lone_sid, []) else None)(
                lookup_shards()),
            "single-shard loss at the master")
        repair_timings = {}
        t_repair = time.perf_counter()
        do_ec_rebuild(env, vid, "bench", shard_map2, [lone_sid],
                      timings=repair_timings, repair="auto")
        repair_wall_s = time.perf_counter() - t_repair
        ok = ok and set(poll(
            lambda: (lambda m: m if set(m) == set(range(TOTAL))
                     else None)(lookup_shards()),
            "all shards back after the repair drill")) == set(range(TOTAL))

        # -- piggyback layout drill: a second (smaller) volume encoded
        # with SW_EC_LAYOUT=piggyback, one data shard destroyed, -repair
        # auto routed to the plane repair. Its repair_bytes_frac lands
        # at the coupled layout's (k+1)/(2k) floor — 0.55 for RS(10,4)
        # — reported beside the trace drill's frac and the full-gather
        # baseline (1.0) so all three repair strategies sit in one
        # record.
        pb_mb = max(size_mb // 4, 8)
        a2 = op.assign(master.url, collection="bench")
        vid2 = int(a2["fid"].split(",")[0])
        written = 0
        i = 0
        while written < (pb_mb << 20):
            i += 1
            op.upload(a2["url"], f"{vid2},{i:x}00000001", chunk,
                      filename=f"p{i}")
            written += len(chunk)
        os.environ["SW_EC_LAYOUT"] = "piggyback"
        try:
            pb_enc = {}
            do_ec_encode(env, vid2, timings=pb_enc)
        finally:
            os.environ.pop("SW_EC_LAYOUT", None)

        def lookup_shards2():
            out2 = get_json(f"http://{master.url}/cluster/ec_lookup"
                            f"?volumeId={vid2}")
            return {int(s): urls for s, urls in out2["shards"].items()}

        pb_map = poll(
            lambda: (lambda m: m if set(m) == set(range(TOTAL))
                     else None)(lookup_shards2()),
            "all piggyback shards at the master")
        pb_sid = 0  # a coupled data shard: the plane-repair fast path
        pb_holder = pb_map[pb_sid][0]
        post_json(f"http://{pb_holder}/admin/ec/unmount?volume={vid2}"
                  f"&shards={pb_sid}")
        post_json(f"http://{pb_holder}/admin/ec/delete_shards"
                  f"?volume={vid2}&collection=bench&shards={pb_sid}")
        pb_map = poll(
            lambda: (lambda m: m if pb_holder not in
                     m.get(pb_sid, []) else None)(lookup_shards2()),
            "piggyback shard loss at the master")
        pb_rep = {}
        t_pb = time.perf_counter()
        do_ec_rebuild(env, vid2, "bench", pb_map, [pb_sid],
                      timings=pb_rep, repair="auto")
        pb_repair_wall_s = time.perf_counter() - t_pb
        ok = ok and set(poll(
            lambda: (lambda m: m if set(m) == set(range(TOTAL))
                     else None)(lookup_shards2()),
            "piggyback shard back after plane repair")) \
            == set(range(TOTAL))
        rep_dev = _dstats.delta(dsnap2)
        # compile/steady split: the headline MB/s must measure the
        # serving path a warm fleet runs, so compile wall (a once-per-
        # process warmup cost, reported on its own) is subtracted from
        # the rebuild wall before the bandwidth division.
        encode_compile_s = enc_dev["compile_seconds_total"]
        rebuild_compile_s = reb_dev["compile_seconds_total"]
        repair_compile_s = rep_dev["compile_seconds_total"]
        rebuild_steady_s = max(rebuild_s - rebuild_compile_s, 1e-9)
        recompiles = (enc_dev["recompiles_total"]
                      + reb_dev["recompiles_total"]
                      + rep_dev["recompiles_total"])
        dstats_now = _dstats.DEVICE_STATS.snapshot()
        if recompiles:
            raise RuntimeError(
                f"cluster rebuild: {recompiles} XLA recompile(s) after "
                f"warmup — width-bucketing regressed "
                f"(offenders: {dstats_now['offenders']})")
        out = {"servers": n_servers, "volume_mb": size_mb,
               "backend": backend, "lost_shards": len(lost),
               "encode_spread_s": round(encode_s, 1),
               # streaming encode+spread split (busy times + overlap;
               # copy mode reports its two serialized phase walls and
               # overlap 0) — the write-path mirror of the gather
               # accounting below
               "encode_mode": enc_timings.get("mode", "stream"),
               "encode_s": round(
                   enc_timings.get("encode_busy_s", 0.0), 2),
               "spread_s": round(
                   enc_timings.get("spread_busy_s", 0.0), 2),
               "encode_overlap_frac": round(
                   enc_timings.get("overlap_frac", 0.0), 3),
               "spread_mbps": round(
                   enc_timings.get("spread_mbps", 0.0), 1),
               "rebuild_wall_s": round(rebuild_s, 1),
               # XLA compile wall split out of every headline: the
               # steady-state bandwidth is what a warm fleet sustains,
               # compile_s is the once-per-process warmup it pays
               "encode_compile_s": round(encode_compile_s, 2),
               "compile_s": round(rebuild_compile_s, 2),
               "repair_compile_s": round(repair_compile_s, 2),
               "rebuild_steady_s": round(rebuild_steady_s, 1),
               "recompiles": recompiles,
               "recompile_sentinel": dstats_now["sentinel"],
               "xla_compiles": enc_dev["compiles_total"]
               + reb_dev["compiles_total"] + rep_dev["compiles_total"],
               "rebuild_mbps_volume_bytes": round(
                   (size_mb << 20) / rebuild_steady_s / 1e6),
               "gather_s": round(gather_s, 2),
               "compute_s": round(compute_s, 2),
               "mount_s": round(timings.get("mount_s", 0.0), 2),
               "gather_frac": round(gather_s / rebuild_s, 2),
               "compute_frac": round(compute_s / rebuild_s, 2),
               "gathered_shards": timings.get("gathered_shards", 0),
               "dispatches": timings.get("dispatches", 0),
               "bitmat_uploads": timings.get("bitmat_uploads", 0),
               "mesh_dispatches": timings.get("mesh_dispatches", 0),
               "dispatch_width_devices": width_devices,
               "device_busy_frac": busy_frac,
               "rebuild_device_mbps": round(
                   survivor_bytes / stream_s / 1e6) if stream_s else 0,
               # streaming-gather overlap accounting: gather_s/compute_s
               # above are BUSY times in stream mode, so their sum
               # estimates what the serialized copy-then-rebuild flow
               # would have cost; overlap_frac = saved/serialized
               "overlap_frac": round(
                   timings.get("overlap_frac", 0.0), 3),
               "gather_mbps": round(timings.get("gather_mbps", 0.0), 1),
               "gather_busy_s": round(
                   timings.get("gather_busy_s", 0.0), 2),
               "serialized_estimate_s": round(gather_s + compute_s, 2),
               "hedges_fired": timings.get("hedges_fired", 0),
               # hedge-loss attribution + per-holder health (fleet
               # health plane): which holders lost hedge races, how
               # many range reads each holder served, and the cluster
               # /cluster/health worst-observer scores — snapshots of
               # slow-holder detection over time
               "hedges_won": timings.get("hedges_won", 0),
               "hedges_lost": timings.get("hedges_lost", 0),
               "holder_fetches": timings.get("holder_fetches", {}),
               "holder_errors": timings.get("holder_errors", {}),
               "holder_health": _cluster_holder_health(master.url),
               # per-phase {name: seconds} from the rebuilder's spans
               # (gather/plan/dispatch/drain/write) plus the trace id —
               # the full span timeline is at the rebuilder's
               # /admin/traces?trace=<id>
               "phases": timings.get("phases", {}),
               "trace_id": timings.get("trace_id"),
               # single-shard repair drill (trace repair vs the k*shard
               # full-gather baseline; repair_bytes_frac < 1.0 iff the
               # trace path was taken and paid off)
               "repair_mode": repair_timings.get("repair_mode", "?"),
               "repair_bytes_frac": round(
                   repair_timings.get("repair_bytes_frac", 1.0), 3),
               "repair_mbps": round(
                   repair_timings.get("repair_mbps", 0.0), 1),
               "repair_wall_s": round(repair_wall_s, 2),
               "repair_helpers": repair_timings.get("repair_helpers", 0),
               "repair_fallback": repair_timings.get("repair_fallback"),
               # piggyback layout drill (plane repair on the coupled
               # sub-chunk layout vs the same k*shard baseline; the
               # construction's floor is (k+1)/(2k) = 0.55 for RS(10,4),
               # between trace's measured frac and full's 1.0)
               "piggyback_volume_mb": pb_mb,
               "piggyback_repair_mode": pb_rep.get("repair_mode", "?"),
               "piggyback_repair_bytes_frac": round(
                   pb_rep.get("repair_bytes_frac", 1.0), 3),
               "piggyback_repair_wall_s": round(pb_repair_wall_s, 2),
               "piggyback_repair_helpers": pb_rep.get(
                   "repair_helpers", 0),
               "piggyback_repair_fallback": pb_rep.get("repair_fallback"),
               "full_repair_bytes_frac": 1.0,
               "all_shards_restored": ok}
        log(f"cluster rebuild: {out}")
        return out
    finally:
        for vs in servers:
            vs.stop()
        master.stop()
        _shutil.rmtree(workdir, ignore_errors=True)


def measure_cluster_degraded_read(n_needles: int = None,
                                  needle_kb: int = None,
                                  n_servers: int = 3,
                                  readers: int = None,
                                  rounds: int = None) -> dict:
    """Degraded-read serving drill: needles on a destroyed shard served
    by reconstruct-on-read under concurrency. Reports healthy p50/p99,
    the naive per-read reconstruct (SW_EC_DEGRADED_MODE=naive), the
    batched DegradedReadEngine cold and warm, plus batch width, slab
    cache hit ratio and survivor bytes per read — the loss-masked-read
    p99 story next to cluster_rebuild's repair story."""
    import shutil as _shutil
    from seaweedfs_tpu.client import operation as op
    from seaweedfs_tpu.ec.constants import (LARGE_BLOCK_SIZE,
                                            SMALL_BLOCK_SIZE)
    from seaweedfs_tpu.server.http_util import (get_json, http_call,
                                                post_json)
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.storage.types import parse_file_id
    n_needles = n_needles or config.env_int("SW_BENCH_DEGRADED_NEEDLES")
    needle_kb = needle_kb or config.env_int("SW_BENCH_DEGRADED_KB")
    readers = readers or config.env_int("SW_BENCH_DEGRADED_READERS")
    rounds = rounds or config.env_int("SW_BENCH_DEGRADED_ROUNDS")
    backend = config.env_str("SW_BENCH_DEGRADED_BACKEND")
    workdir = tempfile.mkdtemp(prefix="swdegraded_")
    master = MasterServer(port=0, volume_size_limit_mb=64,
                          pulse_seconds=1).start()
    servers = []
    saved_mode = os.environ.get("SW_EC_DEGRADED_MODE")
    try:
        for i in range(n_servers):
            servers.append(VolumeServer(
                port=0, directories=[os.path.join(workdir, f"v{i}")],
                master_url=master.url, pulse_seconds=1,
                max_volume_counts=[30], ec_backend=backend).start())
        rng = np.random.default_rng(11)
        payloads = {}
        for i in range(n_needles):
            data = rng.integers(0, 256, needle_kb << 10,
                                dtype=np.uint8).tobytes()
            fid = op.upload_data(master.url, data, filename=f"d{i}",
                                 collection="bench")
            payloads[fid] = data
        # assignment round-robins over volumes: encode and drill the
        # volume that received the most needles
        by_vid = {}
        for fid in payloads:
            by_vid.setdefault(int(fid.split(",")[0]), []).append(fid)
        vid = max(by_vid, key=lambda v: len(by_vid[v]))
        fids = by_vid[vid]
        payloads = {f: payloads[f] for f in fids}
        import seaweedfs_tpu.shell  # noqa: F401
        from seaweedfs_tpu.shell.command_env import CommandEnv
        from seaweedfs_tpu.shell.command_ec import do_ec_encode
        env = CommandEnv(master.url, out=sys.stderr)
        env.admin_timeout = config.env_float("SW_BENCH_DRILL_TIMEOUT")
        # device-runtime bracketing (servers run in-process): compile
        # wall reports separately per phase, recompiles gate at zero —
        # trivially so on the numpy backend, meaningfully on device ones
        from seaweedfs_tpu.ops import device_stats as _dstats
        dsnap0 = _dstats.DEVICE_STATS.snapshot()
        do_ec_encode(env, vid)
        enc_dev = _dstats.delta(dsnap0)

        def poll(pred, what, timeout=30.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    got = pred()
                except Exception:  # noqa: BLE001 - master mid-update
                    got = None
                if got is not None:
                    return got
                time.sleep(0.1)
            raise TimeoutError(f"degraded drill: {what} not observed "
                               f"within {timeout}s")

        def lookup_shards():
            out = get_json(f"http://{master.url}/cluster/ec_lookup"
                           f"?volumeId={vid}")
            return {int(s): urls for s, urls in out["shards"].items()}

        shard_map = poll(
            lambda: (lambda m: m if set(m) == set(range(TOTAL))
                     else None)(lookup_shards()),
            "all 14 encoded shards at the master")

        # per-needle target shard (first interval), via any server
        # holding the ec volume
        locate_vs = next(s for s in servers
                         if s.store.find_ec_volume(vid) is not None)
        ev = locate_vs.store.find_ec_volume(vid)
        by_sid = {}
        for fid in fids:
            _, key, _ = parse_file_id(fid)
            _, _, ivs = ev.locate_needle(key)
            sid, _ = ivs[0].to_shard_id_and_offset(
                LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE)
            by_sid.setdefault(sid, []).append(fid)
        target_sid, degraded_fids = max(by_sid.items(),
                                        key=lambda kv: len(kv[1]))
        holders = set(shard_map[target_sid])
        serving = next(s for s in servers if s.url not in holders and
                       s.store.find_ec_volume(vid) is not None)

        def drill(fid_list, mode_note, base_url=None):
            base = base_url or serving.url
            lat, errs = [], []
            lock = threading.Lock()

            def worker(tid):
                order = list(fid_list)
                trng = np.random.default_rng(100 + tid)
                for _ in range(rounds):
                    trng.shuffle(order)
                    for fid in order:
                        t0 = time.perf_counter()
                        try:
                            got = http_call(
                                "GET", f"http://{base}/{fid}",
                                timeout=60)
                        except Exception as e:  # noqa: BLE001
                            with lock:
                                errs.append(f"{mode_note} {fid}: {e!r}")
                            continue
                        dt = time.perf_counter() - t0
                        with lock:
                            lat.append(dt)
                        if got != payloads[fid]:
                            with lock:
                                errs.append(
                                    f"{mode_note} {fid}: bytes differ")

            t_wall = time.perf_counter()
            threads = [threading.Thread(target=worker, args=(t,))
                       for t in range(readers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t_wall
            if errs:
                raise RuntimeError(errs[0])
            lat.sort()
            return (lat[len(lat) // 2] * 1e3,
                    lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3,
                    wall)

        healthy_p50, healthy_p99, _ = drill(fids, "healthy")

        # destroy the target shard everywhere
        for holder in sorted(holders):
            post_json(f"http://{holder}/admin/ec/unmount?volume={vid}"
                      f"&shards={target_sid}")
            post_json(f"http://{holder}/admin/ec/delete_shards"
                      f"?volume={vid}&collection=bench"
                      f"&shards={target_sid}")
        poll(lambda: (True if not lookup_shards().get(target_sid)
                      else None),
             "shard loss at the master")

        # naive per-read reconstruct (exactly-k fetch, one-row decode,
        # but no batching / caching / hedging)
        os.environ["SW_EC_DEGRADED_MODE"] = "naive"
        dsnap_naive = _dstats.DEVICE_STATS.snapshot()
        naive_p50, naive_p99, naive_wall = drill(degraded_fids, "naive")
        naive_dev = _dstats.delta(dsnap_naive)

        # batched engine, cold cache
        os.environ.pop("SW_EC_DEGRADED_MODE", None)
        eng = serving.degraded
        eng.invalidate(vid)
        base = eng.snapshot()
        dsnap_batch = _dstats.DEVICE_STATS.snapshot()
        batch_p50, batch_p99, batch_wall = drill(degraded_fids, "batch")
        batch_dev = _dstats.delta(dsnap_batch)
        snap = eng.snapshot()
        d_reads = max(1, snap["reads"] - base["reads"])
        # warm re-read: the slab LRU serves without another gather
        warm_p50, warm_p99, _ = drill(degraded_fids, "warm")
        warm = eng.snapshot()

        # plane trial set: the same warm reads served entirely by the
        # native plane's slab cache — 200 straight from C++, never the
        # 307 hop back to Python
        plane = {}
        if serving.fast_plane is not None and \
                serving.fast_plane.cache_stats() is not None:
            import http.client as _hc

            def plane_status(fid):
                """One-shot GET without redirect following, so the
                plane's own verdict (200 vs 307) is observable."""
                host, port = serving.fast_url.rsplit(":", 1)
                c = _hc.HTTPConnection(host, int(port), timeout=30)
                try:
                    c.request("GET", f"/{fid}")
                    r = c.getresponse()
                    r.read()
                    return r.status
                finally:
                    c.close()

            # warm the plane (a followed read re-publishes any slab
            # evicted since the cold batch), then keep the fids it can
            # serve end-to-end: fully covered by cached + local shards
            for fid in degraded_fids:
                http_call("GET", f"http://{serving.fast_url}/{fid}",
                          timeout=60)
            plane_fids = [f for f in degraded_fids
                          if plane_status(f) == 200]
            if plane_fids:
                cbase = serving.fast_plane.cache_stats()
                tele_base = serving.fast_plane.redirected
                pw_p50, pw_p99, _ = drill(plane_fids, "plane-warm",
                                          base_url=serving.fast_url)
                csnap = serving.fast_plane.cache_stats()
                n_reads = readers * rounds * len(plane_fids)
                served_d = (csnap["degraded_served"]
                            - cbase["degraded_served"])
                plane = {
                    "plane_fids": len(plane_fids),
                    "plane_warm_p50_ms": round(pw_p50, 2),
                    "plane_warm_p99_ms": round(pw_p99, 2),
                    "plane_reads": n_reads,
                    "plane_served": served_d,
                    "plane_degraded_redirects": (
                        csnap["degraded_redirected"]
                        - cbase["degraded_redirected"]),
                    # the acceptance triple: every read served in-plane,
                    # zero hops back to Python, counter == reads exactly
                    "plane_zero_redirect": bool(
                        served_d == n_reads
                        and csnap["degraded_redirected"]
                        == cbase["degraded_redirected"]
                        and serving.fast_plane.redirected == tele_base),
                    "plane_speedup_vs_python_warm": round(
                        warm_p99 / max(pw_p99, 1e-6), 2),
                    "plane_beats_python_warm": bool(pw_p99 < warm_p99),
                }

        # compile/steady split + the recompile gate: compiles may land
        # in the first (naive) degraded phase — that's warmup; a SECOND
        # compile of any (entry, width-bucket) pair anywhere in the
        # drill means bucketing broke and the drill fails loudly.
        recompiles = (enc_dev["recompiles_total"]
                      + naive_dev["recompiles_total"]
                      + batch_dev["recompiles_total"])
        dstats_now = _dstats.DEVICE_STATS.snapshot()
        if recompiles:
            raise RuntimeError(
                f"cluster degraded read: {recompiles} XLA recompile(s) "
                f"after warmup — width-bucketing regressed "
                f"(offenders: {dstats_now['offenders']})")
        naive_compile_s = naive_dev["compile_seconds_total"]
        batch_compile_s = batch_dev["compile_seconds_total"]
        out = {"servers": n_servers, "backend": backend,
               "needles": n_needles, "needle_kb": needle_kb,
               "degraded_needles": len(degraded_fids),
               "readers": readers, "rounds": rounds,
               "healthy_p50_ms": round(healthy_p50, 2),
               "healthy_p99_ms": round(healthy_p99, 2),
               "degraded_naive_p50_ms": round(naive_p50, 2),
               "degraded_naive_p99_ms": round(naive_p99, 2),
               "naive_wall_s": round(naive_wall, 2),
               "degraded_p50_ms": round(batch_p50, 2),
               "degraded_p99_ms": round(batch_p99, 2),
               "batch_wall_s": round(batch_wall, 2),
               "encode_compile_s": round(
                   enc_dev["compile_seconds_total"], 2),
               "compile_s": round(naive_compile_s + batch_compile_s, 2),
               "naive_steady_s": round(
                   max(naive_wall - naive_compile_s, 0.0), 2),
               "batch_steady_s": round(
                   max(batch_wall - batch_compile_s, 0.0), 2),
               "recompiles": recompiles,
               "recompile_sentinel": dstats_now["sentinel"],
               "batch_width_max": snap["max_batch_requests"],
               "batch_width_avg": round(
                   (snap["batched_requests"] - base["batched_requests"])
                   / max(1, snap["batches"] - base["batches"]), 2),
               "survivor_bytes_per_read": round(
                   (snap["survivor_bytes"] - base["survivor_bytes"])
                   / d_reads),
               "cache_hit_ratio_warm": round(warm["cache_hit_ratio"], 3),
               "warm_p50_ms": round(warm_p50, 2),
               "warm_p99_ms": round(warm_p99, 2),
               "batched_beats_naive": bool(batch_wall < naive_wall
                                           and batch_p99 < naive_p99)}
        out.update(plane)
        log(f"cluster degraded read: {out}")
        return out
    finally:
        if saved_mode is None:
            os.environ.pop("SW_EC_DEGRADED_MODE", None)
        else:
            os.environ["SW_EC_DEGRADED_MODE"] = saved_mode
        for vs in servers:
            vs.stop()
        master.stop()
        _shutil.rmtree(workdir, ignore_errors=True)


def measure_cluster_scrub_repair(n_volumes: int = None,
                                 n_needles: int = None,
                                 needle_kb: int = None,
                                 n_servers: int = 3,
                                 readers: int = None) -> dict:
    """Rolling-failure integrity drill: many EC volumes under live
    reads, one gets a byte flipped on disk and another loses a shard.
    Reports corruption detection latency, scrub MB/s, scrub overhead on
    the foreground p99, and time-to-re-protection p50/p99 across both
    incident kinds — the integrity-plane story next to the degraded
    and rebuild drills."""
    import shutil as _shutil
    from seaweedfs_tpu.client import operation as op
    from seaweedfs_tpu.ec import to_ext
    from seaweedfs_tpu.server.http_util import get_json, http_call, \
        post_json
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    n_volumes = n_volumes or config.env_int("SW_BENCH_SCRUB_VOLUMES")
    n_needles = n_needles or config.env_int("SW_BENCH_SCRUB_NEEDLES")
    needle_kb = needle_kb or config.env_int("SW_BENCH_SCRUB_KB")
    readers = readers or config.env_int("SW_BENCH_SCRUB_READERS")
    rate_mbps = config.env_float("SW_EC_SCRUB_RATE_MBPS")
    workdir = tempfile.mkdtemp(prefix="swscrub_")
    saved = {k: os.environ.get(k)
             for k in ("SW_REPAIR_INTERVAL_S", "SW_EC_SCRUB_IDLE_S")}
    os.environ["SW_REPAIR_INTERVAL_S"] = "0.5"
    os.environ["SW_EC_SCRUB_IDLE_S"] = "0"  # manual triggers only
    master = MasterServer(port=0, volume_size_limit_mb=64,
                          pulse_seconds=1).start()
    servers = []
    try:
        for i in range(n_servers):
            servers.append(VolumeServer(
                port=0, directories=[os.path.join(workdir, f"v{i}")],
                master_url=master.url, pulse_seconds=1,
                max_volume_counts=[30], ec_backend="numpy").start())
        rng = np.random.default_rng(23)
        payloads = {}   # fid -> bytes
        by_vid = {}     # vid -> [fids]
        vid_coll = {}   # vid -> collection (volumes are per-collection)
        for v in range(n_volumes):
            coll = f"sc{v}"
            for i in range(n_needles):
                data = rng.integers(0, 256, needle_kb << 10,
                                    dtype=np.uint8).tobytes()
                fid = op.upload_data(master.url, data,
                                     filename=f"s{v}_{i}",
                                     collection=coll)
                payloads[fid] = data
                vid = int(fid.split(",")[0])
                by_vid.setdefault(vid, []).append(fid)
                vid_coll[vid] = coll
        import seaweedfs_tpu.shell  # noqa: F401
        from seaweedfs_tpu.shell.command_env import CommandEnv
        from seaweedfs_tpu.shell.command_ec import do_ec_encode
        env = CommandEnv(master.url, out=sys.stderr)
        env.admin_timeout = config.env_float("SW_BENCH_DRILL_TIMEOUT")
        for vid in sorted(by_vid):
            do_ec_encode(env, vid)

        def poll(pred, what, timeout=60.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    got = pred()
                except Exception:  # noqa: BLE001 - cluster mid-update
                    got = None
                if got is not None:
                    return got
                time.sleep(0.1)
            raise TimeoutError(f"scrub drill: {what} not observed "
                               f"within {timeout}s")

        def lookup_shards(vid):
            out = get_json(f"http://{master.url}/cluster/ec_lookup"
                           f"?volumeId={vid}")
            return {int(s): urls for s, urls in out["shards"].items()}

        for vid in sorted(by_vid):
            poll(lambda v=vid: (lambda m: m if set(m) ==
                                set(range(TOTAL)) else None)(
                lookup_shards(v)),
                f"all {TOTAL} shards of volume {vid} at the master")

        def read_all(fids, note):
            lat = []
            errs = []
            lock = threading.Lock()

            def worker(tid):
                order = list(fids)
                trng = np.random.default_rng(300 + tid)
                trng.shuffle(order)
                for fid in order:
                    vs = servers[tid % len(servers)]
                    t0 = time.perf_counter()
                    try:
                        got = http_call("GET",
                                        f"http://{vs.url}/{fid}",
                                        timeout=60)
                    except Exception as e:  # noqa: BLE001
                        with lock:
                            errs.append(f"{note} {fid}: {e!r}")
                        continue
                    dt = time.perf_counter() - t0
                    with lock:
                        lat.append(dt)
                    if got != payloads[fid]:
                        with lock:
                            errs.append(f"{note} {fid}: bytes differ")

            threads = [threading.Thread(target=worker, args=(t,))
                       for t in range(readers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errs:
                raise RuntimeError(errs[0])
            lat.sort()
            return (lat[len(lat) // 2] * 1e3,
                    lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3)

        all_fids = list(payloads)
        healthy_p50, healthy_p99 = read_all(all_fids * 3, "healthy")

        # foreground p99 while a rate-limited scrub pass runs
        scrub_threads = [threading.Thread(
            target=lambda s=s: s.scrub.run_pass(force=True),
            daemon=True) for s in servers]
        for t in scrub_threads:
            t.start()
        scrub_p50, scrub_p99 = read_all(all_fids * 3, "during_scrub")
        for t in scrub_threads:
            t.join(timeout=300)
        scrub_mbps = max(s.scrub.snapshot()["last_pass_mbps"]
                         for s in servers)
        clean_findings = sum(s.scrub.snapshot()["findings"]
                             for s in servers)
        if clean_findings:
            raise RuntimeError(
                f"false positives: {clean_findings} findings on clean "
                f"volumes")

        # incident 1: silent corruption — flip one byte on disk
        vid_a = sorted(by_vid)[0]
        victim = next(s for s in servers
                      if s.store.find_ec_volume(vid_a) is not None)
        ev = victim.store.find_ec_volume(vid_a)
        sid_a = sorted(ev.shards)[0]
        path = ev.base_name + to_ext(sid_a)
        with open(path, "r+b") as f:
            f.seek(os.path.getsize(path) // 2)
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0xFF]))
        t_corrupt = time.perf_counter()
        post_json(f"http://{victim.url}/admin/ec/scrub?volume={vid_a}")

        def corrupt_incident():
            view = get_json(f"http://{master.url}/cluster/repairs")
            for inc in view["open"] + view["resolved_recent"]:
                if inc["kind"] == "corruption" \
                        and inc["volume"] == vid_a:
                    return inc
            return None

        poll(corrupt_incident, "corruption incident at the master")
        detection_s = time.perf_counter() - t_corrupt

        def corrupt_resolved():
            view = get_json(f"http://{master.url}/cluster/repairs")
            for inc in view["resolved_recent"]:
                if inc["kind"] == "corruption" \
                        and inc["volume"] == vid_a:
                    return inc
            return None

        inc_a = poll(corrupt_resolved, "corruption repair", timeout=120)
        read_all(by_vid[vid_a], "restored_corruption")

        # incident 2: shard loss on a different volume
        vid_b = sorted(by_vid)[-1]
        shards_b = lookup_shards(vid_b)
        sid_b = max(shards_b)
        for holder in shards_b[sid_b]:
            post_json(f"http://{holder}/admin/ec/unmount"
                      f"?volume={vid_b}&shards={sid_b}")
            post_json(f"http://{holder}/admin/ec/delete_shards"
                      f"?volume={vid_b}&collection={vid_coll[vid_b]}"
                      f"&shards={sid_b}")

        def lost_resolved():
            view = get_json(f"http://{master.url}/cluster/repairs"
                            f"?refresh=1")
            for inc in view["resolved_recent"]:
                if inc["kind"] == "lost_shard" \
                        and inc["volume"] == vid_b \
                        and inc["shard"] == sid_b:
                    return inc
            return None

        inc_b = poll(lost_resolved, "lost-shard repair", timeout=120)
        read_all(by_vid[vid_b], "restored_loss")

        view = get_json(f"http://{master.url}/cluster/repairs")
        ttr = view["time_to_re_protection"]
        out = {"servers": n_servers, "volumes": len(by_vid),
               "needles": len(payloads),
               "needle_kb": needle_kb, "readers": readers,
               "scrub_rate_mbps": rate_mbps,
               "scrub_mbps": round(scrub_mbps, 2),
               "healthy_p50_ms": round(healthy_p50, 2),
               "healthy_p99_ms": round(healthy_p99, 2),
               "during_scrub_p50_ms": round(scrub_p50, 2),
               "during_scrub_p99_ms": round(scrub_p99, 2),
               "detection_latency_s": round(detection_s, 3),
               "corruption_ttr_s": inc_a["time_to_re_protection_s"],
               "lost_shard_ttr_s": inc_b["time_to_re_protection_s"],
               "ttr_p50_s": ttr["p50_s"], "ttr_p99_s": ttr["p99_s"],
               "false_positives": 0,
               "restored_bit_identical": True}
        log(f"cluster scrub/repair: {out}")
        return out
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        # master first: its repair loop must die before holders vanish,
        # or it floods the log with doomed rebuilds against a collapsing
        # topology
        master.stop()
        for vs in servers:
            vs.stop()
        _shutil.rmtree(workdir, ignore_errors=True)


def measure_cluster_tiering(n_needles: int = None,
                            needle_kb: int = None,
                            n_servers: int = 3,
                            readers: int = None,
                            writers: int = None,
                            rate_mbps: float = None) -> dict:
    """f4 write-through tiering drill: one sealed hot volume is demoted
    to EC through the shared stripe transport — rate-capped — WHILE
    foreground readers hammer its needles and foreground writers keep
    landing new data in other volumes. There is no drain window: reads
    hit the hot replica until the EC mount flips (the replica delete),
    then the stripe. Reports foreground p50/p99 during demotion vs
    healthy, the demotion MB/s under the cap, zero failed/blocked
    client writes, and bit-identical read-back across the flip."""
    import shutil as _shutil
    from seaweedfs_tpu.client import operation as op
    from seaweedfs_tpu.server.http_util import get_json, post_json
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    n_needles = n_needles or config.env_int("SW_BENCH_TIER_NEEDLES")
    needle_kb = needle_kb or config.env_int("SW_BENCH_TIER_KB")
    readers = readers or config.env_int("SW_BENCH_TIER_READERS")
    writers = writers or config.env_int("SW_BENCH_TIER_WRITERS")
    if rate_mbps is None:
        rate_mbps = config.env_float("SW_BENCH_TIER_RATE_MBPS")
    workdir = tempfile.mkdtemp(prefix="swtier_")
    master = MasterServer(
        port=0, volume_size_limit_mb=config.env_int("SW_BENCH_TIER_MB"),
        pulse_seconds=1).start()
    servers = []
    try:
        for i in range(n_servers):
            servers.append(VolumeServer(
                port=0, directories=[os.path.join(workdir, f"v{i}")],
                master_url=master.url, pulse_seconds=1,
                max_volume_counts=[20], ec_backend="numpy").start())

        # fill ONE volume of its own collection: assigns round-robin
        # across the collection's volumes, keep only the first vid
        rng = np.random.default_rng(47)
        a0 = op.assign(master.url, collection="tier")
        vid = int(a0["fid"].split(",")[0])
        payloads = {}
        hot_bytes = 0
        attempts = 0
        while len(payloads) < n_needles and attempts < n_needles * 30:
            attempts += 1
            a = a0 or op.assign(master.url, collection="tier")
            a0 = None
            if int(a["fid"].split(",")[0]) != vid:
                continue
            data = rng.integers(0, 256, needle_kb << 10,
                                dtype=np.uint8).tobytes()
            op.upload(a["url"], a["fid"], data,
                      filename=f"t{len(payloads)}")
            payloads[a["fid"]] = data
            hot_bytes += len(data)
        if len(payloads) < n_needles:
            raise RuntimeError(
                f"could not land {n_needles} needles on volume {vid}")

        # seal it — readonly on every holder, then wait for the
        # master's heartbeat view (the tierer scans that view)
        for vs in servers:
            if vs.store.find_volume(vid):
                post_json(f"http://{vs.url}/admin/volume/readonly"
                          f"?volume={vid}")
                vs.heartbeat_once()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            vols = get_json(
                f"http://{master.url}/cluster/volumes")["volumes"]
            if any(r.get("read_only")
                   for r in vols.get(str(vid), [])):
                break
            time.sleep(0.1)
        else:
            raise TimeoutError(f"volume {vid} never sealed at master")

        def pct(lat):
            lat = sorted(lat)
            if not lat:
                return 0.0, 0.0
            return (lat[len(lat) // 2] * 1e3,
                    lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3)

        def fg_load(run, note):
            """The foreground: readers hammer the sealed volume's
            needles, writers keep landing fresh needles (assigns avoid
            the sealed volume by construction) — while run() executes
            in this thread. The SAME load shape runs for the healthy
            baseline and the demotion window, so the p99 ratio
            isolates the demotion itself, not the writer traffic."""
            stop = threading.Event()
            lat, rerr, wlat, wfail = [], [], [], []
            lock = threading.Lock()
            fids = list(payloads)

            def hammer(tid):
                i = tid
                while not stop.is_set():
                    fid = fids[i % len(fids)]
                    t0 = time.perf_counter()
                    try:
                        got = op.read_file(master.url, fid)
                    except Exception as e:  # noqa: BLE001
                        with lock:
                            rerr.append(f"{note} {fid}: {e!r}")
                        continue
                    dt = time.perf_counter() - t0
                    with lock:
                        lat.append(dt)
                        if got != payloads[fid]:
                            rerr.append(f"{note} {fid}: bytes differ")
                    i += 1

            def writer(tid):
                wrng = np.random.default_rng(700 + tid)
                while not stop.is_set():
                    data = wrng.integers(0, 256, 8 << 10,
                                         dtype=np.uint8).tobytes()
                    t0 = time.perf_counter()
                    try:
                        op.upload_data(master.url, data,
                                       filename=f"w{tid}")
                    except Exception as e:  # noqa: BLE001
                        with lock:
                            wfail.append(repr(e))
                        continue
                    with lock:
                        wlat.append(time.perf_counter() - t0)

            fg = [threading.Thread(target=hammer, args=(t,),
                                   daemon=True)
                  for t in range(readers)]
            fg += [threading.Thread(target=writer, args=(t,),
                                    daemon=True)
                   for t in range(writers)]
            for t in fg:
                t.start()
            try:
                ret = run()
            finally:
                stop.set()
                for t in fg:
                    t.join(timeout=30)
            if rerr:
                raise RuntimeError(rerr[0])
            return ret, lat, wlat, wfail

        # pacing floor: the producer cap applies to SHARD bytes — all
        # k+m rows, padded up to the EC block layout (a small volume
        # still pushes TOTAL x 1MB-small-block shards)
        from seaweedfs_tpu.ec.encoder import ec_shard_base_size
        shard_bytes = TOTAL * ec_shard_base_size(hot_bytes)
        paced_floor_s = shard_bytes / (rate_mbps * 1e6) \
            if rate_mbps else 0.0
        # healthy baseline under the identical foreground load, for
        # about as long as the demotion will run
        _, lat_h, wlat_h, wfail_h = fg_load(
            lambda: time.sleep(max(2.0, paced_floor_s)), "healthy")
        healthy_p50, healthy_p99 = pct(lat_h)

        # same load across the whole demotion, run synchronously here
        master.tierer.age_s = 0.0        # sealed counts immediately
        master.tierer.rate_mbps = rate_mbps
        states, lat_d, wlat_d, wfail_d = fg_load(
            master.tierer.run_pass, "during_demotion")
        if states.get(vid) != "warm":
            raise RuntimeError(f"demotion did not land: {states}")
        during_p50, during_p99 = pct(lat_d)
        w_lat = wlat_h + wlat_d
        w_fail = wfail_h + wfail_d
        # a write is "blocked" if it stalled well past the per-request
        # noise floor — the no-drain claim is that client writes never
        # wait on the data mover
        blocked = sum(1 for dt in wlat_d if dt > 2.0)

        # across the flip: hot replicas are gone, every byte must come
        # back identical off the EC stripe
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and any(
                vs.store.find_volume(vid) for vs in servers):
            time.sleep(0.1)
        bit_identical = all(op.read_file(master.url, fid) == data
                            for fid, data in payloads.items())
        if not bit_identical:
            raise RuntimeError("post-flip read-back differs")

        snap = master.tierer.snapshot()["volumes"][str(vid)]
        out = {"servers": n_servers, "needles": len(payloads),
               "needle_kb": needle_kb,
               "hot_mb": round(hot_bytes / 1e6, 2),
               "readers": readers, "writers": writers,
               "rate_cap_mbps": rate_mbps,
               "healthy_p50_ms": round(healthy_p50, 2),
               "healthy_p99_ms": round(healthy_p99, 2),
               "during_demotion_p50_ms": round(during_p50, 2),
               "during_demotion_p99_ms": round(during_p99, 2),
               "p99_ratio": round(during_p99 / healthy_p99, 2)
               if healthy_p99 else None,
               "reads_during_demotion": len(lat_d),
               "writes_ok": len(w_lat),
               "failed_writes": len(w_fail),
               "blocked_writes": blocked,
               "max_write_ms": round(max(w_lat) * 1e3, 2)
               if w_lat else 0.0,
               "demotion_wall_s": snap["wall_s"],
               "demotion_mbps": snap["demote_mbps"],
               "rate_cap_engaged": bool(
                   paced_floor_s
                   and snap["wall_s"] >= 0.9 * paced_floor_s),
               "bit_identical": True}
        log(f"cluster tiering: {out}")
        return out
    finally:
        # master first: its tierer/repair loops must die before the
        # holders vanish under them
        master.stop()
        for vs in servers:
            vs.stop()
        _shutil.rmtree(workdir, ignore_errors=True)


def bench_diff_gate(record: dict, drill: str = None):
    """Transport-parity gate: write this run's record next to the
    historical BENCH_r*.json series and auto-diff against the newest
    prior record via tools/bench_diff.py. Classified metrics that
    regressed >20% exit 2 — the gate the unified-transport refactor
    must hold (rebuild/encode throughput within noise of the pre-
    refactor records). SW_BENCH_DIFF=0 disables the diff (the record
    is still written). Standalone drills write BENCH_last_<drill>.json
    wrapped as {drill: record} so their metric names line up with the
    full records' nested extras; full runs append the next
    BENCH_r<NN>.json."""
    import glob
    import re
    repo = os.path.dirname(os.path.abspath(__file__))
    tools = os.path.join(repo, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    try:
        import bench_diff
    except Exception as e:  # noqa: BLE001 - the gate must not kill emit
        log(f"bench_diff unavailable, gate skipped: {e!r}")
        return
    prior = sorted(glob.glob(os.path.join(repo, "BENCH_r[0-9]*.json")))
    wrapped = {drill: record} if drill else dict(record)
    if drill:
        out_path = os.path.join(repo, f"BENCH_last_{drill}.json")
    else:
        nums = [int(re.search(r"BENCH_r(\d+)", p).group(1))
                for p in prior]
        out_path = os.path.join(
            repo, f"BENCH_r{(max(nums) if nums else 0) + 1:02d}.json")
    with open(out_path, "w") as f:
        json.dump(wrapped, f, indent=1, sort_keys=True)
        f.write("\n")
    log(f"bench record written: {out_path}")
    if not config.env_bool("SW_BENCH_DIFF"):
        return
    if not prior:
        log("bench_diff: no prior BENCH_r*.json, gate skipped")
        return
    old_path = prior[-1]
    try:
        report = bench_diff.diff_records(
            bench_diff.load_record(old_path),
            bench_diff.load_record(out_path), threshold=0.2)
    except Exception as e:  # noqa: BLE001 - unreadable prior record
        log(f"bench_diff failed against {old_path}: {e!r}")
        return
    log(bench_diff.render_text(report, old_path, out_path))
    if report["regressions"]:
        log(f"bench_diff GATE: {len(report['regressions'])} metrics "
            f"regressed >20% vs {os.path.basename(old_path)}")
        raise SystemExit(2)


def _jax_provenance() -> dict:
    """Stamp every emitted record with where the math actually ran —
    a CPU-fallback run (tunnel down) must be distinguishable from a
    device run when comparing trajectories across runs."""
    try:
        import jax
        devs = jax.devices()
        return {"jax_platform": jax.default_backend(),
                "jax_backend": devs[0].device_kind if devs else "",
                "jax_device_count": len(devs)}
    except Exception:  # noqa: BLE001 - provenance must never kill emit
        return {"jax_platform": "unavailable", "jax_backend": "",
                "jax_device_count": 0}


def emit(value: float, vs_baseline: float, kind: str, **extras):
    """ONE JSON line whose value/vs_baseline carry the DEFENSIBLE
    comparison for the conditions of this run (VERDICT r3 weak#2):
      device_kernel_chained — the chained-slope device kernel rate vs
        the native CPU in-memory encode: like-for-like compute, both
        free of tunnel RTT and file I/O; the north-star comparison.
      cpu_e2e_* fallbacks — device unreachable/failed: the native CPU
        e2e path against itself (1.0), explicitly marked.
      tpu_e2e_tunnel_bound — kernel figure unavailable but e2e ran:
        the tunnel-bounded e2e, marked as environmental."""
    line = {"metric": "ec_encode_rs10_4_mbps",
            "value": round(value, 1), "unit": "MB/s",
            "vs_baseline": round(vs_baseline, 2),
            "headline_kind": kind}
    line.update(_jax_provenance())
    line.update(extras)
    print(json.dumps(line))
    # every emitted record lands next to the BENCH_r*.json series and
    # is auto-diffed against the newest prior one (exit 2 on >20%
    # regressions; SW_BENCH_DIFF=0 to disable)
    bench_diff_gate(line)


def run_cluster_drill_subprocess(size_mb: int, n_servers: int) -> dict:
    """BASELINE config 5 with `-ec.backend mesh` on the 8-device
    virtual CPU mesh — in a fresh process, because the device-count
    flag must precede the first jax initialization."""
    import subprocess
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["SW_BENCH_CLUSTER_MB"] = str(size_mb)
    env["SW_BENCH_CLUSTER_SERVERS"] = str(n_servers)
    env["SW_BENCH_CLUSTER_BACKEND"] = "mesh"
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--cluster-drill"],
        env=env, capture_output=True, text=True, timeout=1800)
    for raw in out.stdout.splitlines():
        if raw.startswith("CLUSTER_DRILL "):
            got = json.loads(raw.split(" ", 1)[1])
            got["devices"] = "8x virtual cpu"
            log(f"cluster rebuild (cpu mesh subprocess): {got}")
            return got
    raise RuntimeError(
        f"cluster drill subprocess rc={out.returncode}: "
        f"{out.stdout[-200:]} {out.stderr[-300:]}")


def _dp_durable_trial(mode: str, seconds: float, batch_us: int,
                      plane: bool = True) -> dict:
    """One write-phase trial with SW_PLANE_FSYNC_MODE=mode on a SINGLE
    volume, so the fsync-per-append baselines genuinely serialize each
    append behind its own fdatasync — the throughput crater group
    commit exists to fix. The group trial runs with batch_us=0: natural
    batching, riders accumulate while the previous fdatasync is in
    flight (Haystack's needle-log sync discipline). plane=False runs
    the same load against the Python append path (fast_port=-1): the
    pre-PR durable configuration, where every write pays its own
    fdatasync pair inside the Python server."""
    import io
    import shutil as _shutil
    from seaweedfs_tpu.client import operation as op
    from seaweedfs_tpu.command.benchmark import run_native_benchmark
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    knobs = {"SW_PLANE_FSYNC_MODE": mode,
             "SW_PLANE_FSYNC_BATCH_US": str(batch_us)}
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    workdir = tempfile.mkdtemp(prefix=f"swdpdur_{mode}_")
    master = MasterServer(port=0, pulse_seconds=1).start()
    vs = None
    try:
        vs = VolumeServer(port=0,
                          directories=[os.path.join(workdir, "v")],
                          master_url=master.url, pulse_seconds=1,
                          max_volume_counts=[1],
                          fast_port=0 if plane else -1).start()
        deadline = time.monotonic() + 15
        while True:
            try:
                # same collection the benchmark writes into: with a
                # single volume slot, an assign in "" would consume it
                op.assign(master.url, collection="benchmark")
                break
            except Exception:  # noqa: BLE001 - cluster still assembling
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        buf = io.StringIO()
        run_native_benchmark(master.url, file_size=1024,
                             concurrency=config.env_int(
                                 "SW_BENCH_DP_DURABLE_CONNS"),
                             seconds=seconds, pool=1024, out=buf)
        trial = {"mode": mode, "batch_us": batch_us, "plane": plane}
        for raw in buf.getvalue().splitlines():
            if raw.startswith("{") and '"write"' in raw:
                p = json.loads(raw)
                trial["write_rps"] = p["rps"]
                trial["write_errors"] = p["errors"]
        snap = vs.fast_plane.sync_stats() if vs.fast_plane else None
        if snap and snap["batches"]:
            trial["fsync_batches"] = snap["batches"]
            trial["fsync_riders"] = snap["riders"]
            trial["riders_per_batch"] = round(
                snap["riders"] / snap["batches"], 1)
        return trial
    finally:
        if vs is not None:
            vs.stop()
        master.stop()
        _shutil.rmtree(workdir, ignore_errors=True)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def measure_dp_durability(seconds: float = None) -> dict:
    """Durable-mode trial set. The headline claim: group-commit write
    RPS must beat the measured fsync-per-append baseline >=10x while
    holding >=0.4x the non-durable plane path under identical
    load/volume shape. The primary baseline is the pre-PR durable
    configuration — the Python append path paying an fdatasync pair
    per write (plane disabled, mode=always); the >=0.4x-of-off guard
    keeps that ratio from being credited to the native plane itself.
    The native plane's own always mode is reported as a second,
    stricter baseline (informational: on single-core hosts with
    sub-200us fdatasync it converges toward the CPU ceiling)."""
    seconds = seconds or config.env_float("SW_BENCH_DP_DURABLE_SECONDS")

    def isolated(mode, plane=True):
        # drain the previous trial's dirty pages first: background
        # writeback steals CPU from the next trial and a busy journal
        # lets per-append fsyncs piggyback on in-flight commits, so
        # back-to-back trials contaminate each other in BOTH directions
        os.sync()
        time.sleep(1.0)
        return _dp_durable_trial(mode, seconds, 0, plane=plane)

    trials = {"off": isolated("off"),
              "fsync_per_append": isolated("always", plane=False),
              "always": isolated("always"),
              "group": isolated("group")}
    grp = trials["group"].get("write_rps", 0.0)
    base = trials["fsync_per_append"].get("write_rps", 0.0)
    alw = trials["always"].get("write_rps", 0.0)
    off = trials["off"].get("write_rps", 0.0)
    out = {"modes": trials,
           "group_vs_fsync_per_append":
               round(grp / base, 2) if base else None,
           "group_vs_always_native":
               round(grp / alw, 2) if alw else None,
           "group_vs_off": round(grp / off, 2) if off else None,
           "targets": {"group_vs_fsync_per_append_min": 10.0,
                       "group_vs_off_min": 0.4}}
    out["ok"] = bool(base and off and grp / base >= 10.0
                     and grp / off >= 0.4)
    log(f"data-plane durability: group={grp} fsync_per_append={base} "
        f"always_native={alw} off={off} "
        f"-> group_vs_fsync_per_append="
        f"{out['group_vs_fsync_per_append']} "
        f"group_vs_always_native={out['group_vs_always_native']} "
        f"group_vs_off={out['group_vs_off']} ok={out['ok']}")
    return out


def measure_dp_crash_consistency(runs: int = None) -> dict:
    """The group-commit ack contract under fail-stop: kill -9 a durable
    (SW_PLANE_FSYNC_MODE=group) volume server subprocess mid-burst,
    restart on the same directories, and verify EXACT counts — every
    acked needle reads back bit-identical (acked is a subset of
    recovered); needles never acked are reported separately and never
    counted as durable (an unacked duplicate on disk is harmless)."""
    import http.client
    import shutil as _shutil
    import signal as _signal
    import subprocess
    import threading
    from seaweedfs_tpu.client import operation as op
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    runs = runs if runs is not None \
        else config.env_int("SW_BENCH_DP_CRASH_RUNS")
    out = {"runs": [], "acked_total": 0, "acked_lost_total": 0}
    for run_no in range(runs):
        workdir = tempfile.mkdtemp(prefix="swdpcrash_")
        master = MasterServer(port=0, pulse_seconds=1).start()
        child, vs2 = None, None
        try:
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["SW_PLANE_FSYNC_MODE"] = "group"
            env["SW_BENCH_DP_DIR"] = os.path.join(workdir, "v")
            env["SW_BENCH_DP_MASTER"] = master.url
            child = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--dp-crash-server"],
                env=env, stdout=subprocess.PIPE, text=True)
            ready = None
            for raw in child.stdout:
                if raw.startswith("DP_CRASH_READY "):
                    ready = json.loads(raw.split(" ", 1)[1])
                    break
            if ready is None:
                raise RuntimeError("crash-server child never came up")
            fast = ready["fast_url"]
            deadline = time.monotonic() + 15
            while True:
                try:
                    a = op.assign(master.url, count=4000)
                    break
                except Exception:  # noqa: BLE001 - child still pulsing
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
            fids = list(op.expand_batch_fids(a["fid"], int(a["count"])))
            acked = {}        # fid -> payload bytes (response was read)
            attempted = set()  # posted, ack unknown
            lock = threading.Lock()
            killed = threading.Event()
            boundary = "swdpcrashb"
            ctype = f"multipart/form-data; boundary={boundary}"

            def body_for(fid, i):
                data = (f"{fid}|{i}|".encode() * 64)[:1024]
                raw = (f"--{boundary}\r\nContent-Disposition: "
                       f'form-data; name="file"; filename="c.bin"\r\n'
                       f"Content-Type: application/octet-stream"
                       f"\r\n\r\n").encode() + data + \
                    f"\r\n--{boundary}--\r\n".encode()
                return raw, data

            def writer(tid):
                conn = http.client.HTTPConnection(fast, timeout=10)
                for i in range(tid, len(fids), 8):
                    if killed.is_set():
                        break
                    fid = fids[i]
                    raw, data = body_for(fid, i)
                    with lock:
                        attempted.add(fid)
                    try:
                        conn.request("POST", f"/{fid}", body=raw,
                                     headers={"Content-Type": ctype})
                        r = conn.getresponse()
                        r.read()
                        if r.status == 200:
                            with lock:
                                acked[fid] = data
                    except Exception:  # noqa: BLE001 - ack unknown
                        conn.close()
                        if killed.is_set():
                            break
                        conn = http.client.HTTPConnection(fast,
                                                          timeout=10)
                conn.close()

            def killer():
                # fire mid-burst: enough acks to be meaningful, well
                # before the pool drains
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline:
                    with lock:
                        if len(acked) >= 200:
                            break
                    time.sleep(0.002)
                os.kill(child.pid, _signal.SIGKILL)
                killed.set()

            threads = [threading.Thread(target=writer, args=(t,))
                       for t in range(8)] + \
                [threading.Thread(target=killer)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            child.wait(timeout=30)
            # restart on the SAME directories: torn (unacked) tails may
            # truncate, every acked needle must survive bit-identical
            vs2 = VolumeServer(port=0,
                               directories=[os.path.join(workdir, "v")],
                               master_url=master.url, pulse_seconds=1,
                               max_volume_counts=[8]).start()
            lost = []
            for fid, want in acked.items():
                conn = http.client.HTTPConnection(vs2.url, timeout=10)
                conn.request("GET", f"/{fid}")
                r = conn.getresponse()
                got = r.read()
                conn.close()
                if r.status != 200 or got != want:
                    lost.append(fid)
            unacked = [f for f in attempted if f not in acked]
            unacked_landed = 0
            for fid in unacked:
                conn = http.client.HTTPConnection(vs2.url, timeout=10)
                conn.request("GET", f"/{fid}")
                r = conn.getresponse()
                r.read()
                conn.close()
                if r.status == 200:
                    unacked_landed += 1
            rec = {"acked": len(acked), "acked_lost": len(lost),
                   "unacked_attempts": len(unacked),
                   "unacked_landed_harmless": unacked_landed}
            if lost:
                rec["lost_fids"] = lost[:10]
            out["runs"].append(rec)
            out["acked_total"] += len(acked)
            out["acked_lost_total"] += len(lost)
            log(f"crash drill run {run_no + 1}/{runs}: {rec}")
        finally:
            if child is not None and child.poll() is None:
                child.kill()
                child.wait()
            if vs2 is not None:
                vs2.stop()
            master.stop()
            _shutil.rmtree(workdir, ignore_errors=True)
    out["ok"] = out["acked_lost_total"] == 0 and out["acked_total"] > 0
    return out


def measure_data_plane(seconds: float = None) -> dict:
    """The reference's published headline benchmark (README.md:477-522,
    `weed benchmark`: 15,708 writes/s and 47,019 reads/s of 1KB files):
    an in-process master+volume server driven by the C++ keep-alive
    load engine (`weed benchmark -native`), so the number measures the
    servers, not the Python client. Writes land on the native plane's
    fast POST path, reads on its fast GET path; `errors` must be 0 for
    the number to count."""
    import io
    import shutil as _shutil
    from seaweedfs_tpu.command.benchmark import run_native_benchmark
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    seconds = seconds or config.env_float("SW_BENCH_DP_SECONDS")
    workdir = tempfile.mkdtemp(prefix="swdp_")
    master = MasterServer(port=0, pulse_seconds=1).start()
    vs = None
    try:
        vs = VolumeServer(port=0,
                          directories=[os.path.join(workdir, "v")],
                          master_url=master.url, pulse_seconds=1,
                          max_volume_counts=[8]).start()
        # writable volume available (growth on demand + immediate
        # heartbeat push) — poll an assign instead of sleeping a pulse
        from seaweedfs_tpu.client import operation as op
        deadline = time.monotonic() + 15
        while True:
            try:
                op.assign(master.url)
                break
            except Exception:  # noqa: BLE001 - cluster still assembling
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        buf = io.StringIO()
        run_native_benchmark(master.url, file_size=1024,
                             concurrency=config.env_int("SW_BENCH_DP_CONNS"),
                             seconds=seconds, pool=2048, out=buf)
        out = {}
        for raw in buf.getvalue().splitlines():
            if not raw.startswith("{"):
                continue
            p = json.loads(raw)
            key = "write" if p["phase"] == "write" else "read"
            out[f"{key}_rps"] = p["rps"]
            out[f"{key}_errors"] = p["errors"]
        # reference README req/s on its MacBook-i7 run (BASELINE.md)
        out["vs_ref_write_15708"] = round(out["write_rps"] / 15708.23, 2)
        out["vs_ref_read_47019"] = round(out["read_rps"] / 47019.38, 2)
        out["file_size"] = 1024
        out["note"] = ("native C++ data plane under the native load "
                       "engine, 1KB files; reference numbers were "
                       "measured on different hardware (MacBook i7)")
        log(f"data plane: {out}")
    finally:
        if vs is not None:
            vs.stop()
        master.stop()
        _shutil.rmtree(workdir, ignore_errors=True)
    # durable-mode trial set + kill -9 crash-consistency drill; each is
    # fault-isolated so the non-durable headline survives a miss
    if config.env_float("SW_BENCH_DP_DURABLE_SECONDS") > 0:
        try:
            out["durability"] = measure_dp_durability()
        except Exception as e:  # noqa: BLE001 - secondary
            log(f"data-plane durability trials failed: {e!r}")
    if config.env_int("SW_BENCH_DP_CRASH_RUNS") > 0:
        try:
            out["crash_consistency"] = measure_dp_crash_consistency()
        except Exception as e:  # noqa: BLE001 - secondary
            log(f"data-plane crash drill failed: {e!r}")
    return out


def _plane_quantile_us(buckets, total: int, q: float) -> float:
    """Quantile estimate from the plane's non-cumulative latency
    buckets ([(bound_us or None, count), ...]); returns the upper bound
    of the bucket the quantile falls in."""
    if not total:
        return 0.0
    target = q * total
    cum = 0
    last = 0.0
    for bound, count in buckets:
        cum += count
        if cum >= target:
            return float(bound) if bound is not None else last * 2
        if bound is not None:
            last = float(bound)
    return last


def measure_cluster_plane_read() -> dict:
    """`cluster_plane_read`: the hot-path observability drill — keep-
    alive GETs against the native plane with telemetry on, reporting the
    plane's OWN latency quantiles (from the in-plane histogram), the
    redirect ratio and slow-ring depth, then the same read pass with
    telemetry off (the SW_PLANE_STATS=0 escape hatch toggles the same
    atomic) to assert the counters+clock cost is in-noise."""
    import http.client
    import shutil as _shutil
    from seaweedfs_tpu.server import native_plane
    from seaweedfs_tpu.server.http_util import post_json, post_multipart
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    if not native_plane.available():
        raise RuntimeError("native plane unavailable")
    workdir = tempfile.mkdtemp(prefix="swplane_")
    master = MasterServer(port=0, pulse_seconds=1).start()
    vs = None
    try:
        vs = VolumeServer(port=0,
                          directories=[os.path.join(workdir, "v")],
                          master_url=master.url, pulse_seconds=1,
                          max_volume_counts=[8],
                          ec_backend="numpy").start()
        assert vs.fast_plane is not None, "plane failed to start"
        paths = []
        deadline = time.monotonic() + 15
        for i in range(128):
            while True:
                try:
                    a = post_json(f"http://{master.url}/dir/assign", {})
                    break
                except Exception:  # noqa: BLE001 - cluster assembling
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
            post_multipart(f"http://{a['url']}/{a['fid']}", "b.bin",
                           b"plane-bench|%04d|" % i * 64,
                           "application/octet-stream")
            paths.append("/" + a["fid"])
        host, port = vs.fast_url.split(":")

        def read_pass(n):
            lat = []
            c = http.client.HTTPConnection(host, int(port), timeout=10)
            try:
                for i in range(n):
                    t0 = time.perf_counter()
                    c.request("GET", paths[i % len(paths)])
                    r = c.getresponse()
                    r.read()
                    lat.append(time.perf_counter() - t0)
                    if r.status != 200:
                        raise RuntimeError(f"plane status {r.status}")
            finally:
                c.close()
            lat.sort()
            return lat

        read_pass(200)   # warm the mirror, the page cache, the client
        n = 2000
        on_p50, off_p50 = [], []
        client_lat = None
        for _ in range(max(2, config.env_int("SW_BENCH_TRIALS"))):
            vs.fast_plane.set_stats_enabled(True)
            lat = read_pass(n)
            client_lat = lat
            on_p50.append(lat[len(lat) // 2])
            vs.fast_plane.set_stats_enabled(False)
            lat = read_pass(n)
            off_p50.append(lat[len(lat) // 2])
        vs.fast_plane.set_stats_enabled(True)
        snap = vs.fast_plane.stats()
        total = snap["lat_count"]
        requests = max(1, snap["requests"])
        out = {
            "reads": n * len(on_p50),
            "plane_p50_us": _plane_quantile_us(snap["buckets"], total,
                                               0.50),
            "plane_p99_us": _plane_quantile_us(snap["buckets"], total,
                                               0.99),
            "client_p50_us": round(client_lat[len(client_lat) // 2]
                                   * 1e6, 1),
            "client_p99_us": round(
                client_lat[int(len(client_lat) * 0.99)] * 1e6, 1),
            "redirect_ratio": round(snap["redirects"] / requests, 4),
            "slow_ring_depth": len(vs.fast_plane.slow_requests()),
        }
        # best-of-trials is stable against scheduler noise; the
        # telemetry cost per request is tens of ns against a >=50us
        # loopback request, so anything past 15%+10us is a regression,
        # not noise
        best_on, best_off = min(on_p50), min(off_p50)
        out["stats_on_p50_us"] = round(best_on * 1e6, 1)
        out["stats_off_p50_us"] = round(best_off * 1e6, 1)
        out["overhead_pct"] = round(
            (best_on - best_off) / best_off * 100, 2)
        out["in_noise"] = best_on <= best_off * 1.15 + 10e-6
        assert out["in_noise"], \
            f"plane telemetry overhead out of noise: {out}"
        log(f"cluster plane read: {out}")
        return out
    finally:
        if vs is not None:
            vs.stop()
        master.stop()
        _shutil.rmtree(workdir, ignore_errors=True)


def secondary_configs(device_ok: bool, chained_by_geo: dict) -> dict:
    """BASELINE configs 3-5 plus the reference's own req/s headline,
    each scaled by env and individually fault-isolated (they report
    alongside the headline, never instead of it)."""
    extras = {}
    try:
        extras["data_plane"] = measure_data_plane()
    except Exception as e:  # noqa: BLE001 - secondary
        log(f"data-plane bench failed: {e!r}")
    try:
        extras["rs_geometries"] = measure_geometries(
            config.env_int("SW_BENCH_GEO_MB"),
            chained_by_geo)
    except Exception as e:  # noqa: BLE001 - secondary
        log(f"geometry bench failed: {e!r}")
    try:
        extras["batched_small_needles"] = measure_batched_small_needles(
            config.env_int("SW_BENCH_SMALL_VOLS"),
            config.env_int("SW_BENCH_SMALL_NEEDLES"))
    except Exception as e:  # noqa: BLE001 - secondary
        log(f"small-needle bench failed: {e!r}")
    # hot-path observability drill: the plane's own latency quantiles,
    # redirect ratio and slow-ring depth, plus the telemetry-overhead
    # in-noise assertion vs the SW_PLANE_STATS=0 escape hatch
    try:
        extras["cluster_plane_read"] = measure_cluster_plane_read()
    except Exception as e:  # noqa: BLE001 - secondary
        log(f"cluster plane-read bench failed: {e!r}")
    # loss-masked reads under live traffic: healthy vs degraded p99,
    # batched engine vs naive per-read reconstruct
    try:
        extras["cluster_degraded_read"] = measure_cluster_degraded_read()
    except Exception as e:  # noqa: BLE001 - secondary
        log(f"cluster degraded-read bench failed: {e!r}")
    # rolling-failure integrity drill: scrub detection latency, scrub
    # overhead on the foreground p99, and time-to-re-protection for a
    # corruption and a lost-shard incident
    try:
        extras["cluster_scrub_repair"] = measure_cluster_scrub_repair()
    except Exception as e:  # noqa: BLE001 - secondary
        log(f"cluster scrub/repair bench failed: {e!r}")
    # f4 write-through tiering: hot->warm demotion through the shared
    # stripe transport under live reads/writes, rate-capped, no drain
    try:
        extras["cluster_tiering"] = measure_cluster_tiering()
    except Exception as e:  # noqa: BLE001 - secondary
        log(f"cluster tiering bench failed: {e!r}")
    # config 5 with a DEVICE backend (VERDICT r3 weak#5): the virtual
    # CPU mesh always (subprocess), plus the live single-chip mesh
    # when the tunnel is up
    try:
        extras["cluster_rebuild"] = run_cluster_drill_subprocess(
            config.env_int("SW_BENCH_CLUSTER_MB"),
            config.env_int("SW_BENCH_CLUSTER_SERVERS"))
    except Exception as e:  # noqa: BLE001 - secondary
        log(f"cluster rebuild (cpu mesh) failed: {e!r}")
    if device_ok:
        try:
            extras["cluster_rebuild_device"] = measure_cluster_rebuild(
                config.env_int("SW_BENCH_CLUSTER_TPU_MB"),
                config.env_int("SW_BENCH_CLUSTER_SERVERS"),
                backend="mesh")
        except Exception as e:  # noqa: BLE001 - secondary
            log(f"cluster rebuild (device mesh) failed: {e!r}")
    return extras


def main():
    # --require-tpu: CI/perf-gate mode. The default behavior degrades to
    # a clearly-labeled CPU line when the device tunnel is down, which
    # is right for exploratory runs but lets a regression gate silently
    # measure the wrong backend. With the flag, a CPU fallback is a
    # hard failure instead.
    require_tpu = "--require-tpu" in sys.argv[1:]
    dat_mb = config.env_int("SW_BENCH_DAT_MB")
    slab_mb = config.env_int("SW_BENCH_SLAB_MB")
    init_timeout = config.env_float("SW_BENCH_INIT_TIMEOUT")
    user_dir = config.env_str("SW_BENCH_DIR")
    workdir = user_dir or tempfile.mkdtemp(prefix="swbench_")
    os.makedirs(workdir, exist_ok=True)
    base = os.path.join(workdir, "1")
    try:
        dat_size = generate_dat(base + ".dat", dat_mb)

        cpu_mbps = measure_cpu_e2e(base, dat_size)
        cpu_digests = shard_digests(base)
        try:
            cpu_rebuild = measure_cpu_rebuild(base, dat_size)
        except Exception as e:  # noqa: BLE001 - secondary figure
            log(f"cpu rebuild measurement failed: {e!r}")
            cpu_rebuild = 0.0
        remove_shards(base)
        cpu_inmem = measure_cpu_inmem(slab_mb)

        devices = init_device(init_timeout)
        retry_log = [{"attempt": 1, "t_unix": round(time.time()),
                      "ok": devices is not None}]
        if devices is None:
            # device-free phases run while the tunnel gets more chances
            # to come up; the retry window is spent, not slept away —
            # except under --require-tpu, where a gate wants the
            # verdict, not CPU-only side figures it would discard
            late_secondary = {} if require_tpu \
                else secondary_configs(False, {})
            devices = init_device_retrying(retry_log)
            if devices is None:
                if require_tpu:
                    log("FATAL: --require-tpu set but the device "
                        f"backend never came up ({len(retry_log)} "
                        "attempts); refusing to emit a CPU fallback "
                        "line")
                    raise SystemExit(2)
                # the emitted line must never pass off the CPU number as
                # a healthy TPU result: mark the condition explicitly
                emit(cpu_mbps, 1.0, "cpu_e2e_device_unreachable",
                     note=("TPU tunnel unreachable across all retry "
                           "attempts; value is the native CPU e2e path"),
                     device_init_attempts=retry_log,
                     cpu_inmem_mbps=round(cpu_inmem),
                     cpu_rebuild_mbps=round(cpu_rebuild),
                     **late_secondary)
                return
            # device arrived late: spend the remaining window on the
            # defensible kernel headline, skip the multi-GB e2e phase
            log(f"devices (late, attempt {len(retry_log)}): {devices}")
            chained_by_geo = {}
            for k, m in ((K, M), (6, 3), (20, 4)):
                try:
                    chained_by_geo[(k, m)] = measure_device_chained(
                        slab_mb, k, m)
                except Exception as e:  # noqa: BLE001
                    log(f"chained rs({k},{m}) failed: {e!r}")
            chained, chained_diag = chained_by_geo.get((K, M),
                                                       (0.0, {}))
            if chained and cpu_inmem:
                emit(chained, chained / cpu_inmem,
                     "device_kernel_chained",
                     chained_fit=chained_diag,
                     cpu_inmem_mbps=round(cpu_inmem),
                     cpu_rebuild_mbps=round(cpu_rebuild),
                     device_init_attempts=retry_log,
                     chained_by_geo_mbps={
                         f"rs({k},{m})": round(v[0])
                         for (k, m), v in chained_by_geo.items()},
                     note="device up on retry; kernel headline only, "
                          "e2e skipped to fit the remaining window",
                     **late_secondary)
            else:
                # the headline rs(K,M) kernel (or the CPU denominator)
                # failed — but keep whatever secondary geometries DID
                # measure; they are paid-for device evidence
                if require_tpu:
                    log("FATAL: --require-tpu set but the headline "
                        "device measurement failed after late init")
                    raise SystemExit(2)
                emit(cpu_mbps, 1.0, "cpu_e2e_device_failed_midrun",
                     note="device up on retry but the headline rs(10,4)"
                          " kernel measurement failed; value is the "
                          "native CPU e2e path",
                     device_init_attempts=retry_log,
                     cpu_inmem_mbps=round(cpu_inmem),
                     cpu_rebuild_mbps=round(cpu_rebuild),
                     chained_by_geo_mbps={
                         f"rs({k},{m})": round(v[0])
                         for (k, m), v in chained_by_geo.items()
                         if v and v[0]},
                     **late_secondary)
            return
        log(f"devices: {devices}")
        # chained kernel figures FIRST, on a quiet device: measured after
        # the multi-GB e2e phase they read 20x low (observed 1.6 GB/s
        # post-e2e vs 37-38 GB/s fresh — leftover process/relay state).
        # All three geometries here, so the per-geometry numbers are
        # slope-derived too (not RTT-dominated per-call artifacts).
        chained_by_geo = {}
        for k, m in ((K, M), (6, 3), (20, 4)):
            try:
                chained_by_geo[(k, m)] = measure_device_chained(
                    slab_mb, k, m)
            except Exception as e:  # noqa: BLE001 - diagnosed below
                log(f"chained rs({k},{m}) measurement failed: {e!r}")
        chained, chained_diag = chained_by_geo.get((K, M), (0.0, {}))
        try:
            h2d, d2h = probe_link()
            tpu_mbps, stages = measure_tpu_e2e(base, dat_size, slab_mb)
        except Exception as e:  # noqa: BLE001 - tunnel flakiness: fall back
            log(f"tpu bench failed: {e!r}")
            secondary = secondary_configs(False, chained_by_geo)
            if chained and cpu_inmem:
                # the kernel figure was measured before the failure and
                # is the one device metric robust to it: it IS the
                # defensible headline
                emit(chained, chained / cpu_inmem,
                     "device_kernel_chained",
                     chained_fit=chained_diag,
                     cpu_inmem_mbps=round(cpu_inmem),
                     cpu_rebuild_mbps=round(cpu_rebuild),
                     e2e_tunnel={"error": f"{e!r:.120}"},
                     note="e2e phase failed mid-run (tunnel); kernel "
                          "chained-slope measured before it",
                     **secondary)
            else:
                if require_tpu:
                    log("FATAL: --require-tpu set but the TPU e2e "
                        f"phase failed mid-run: {e!r:.120}")
                    raise SystemExit(2)
                emit(cpu_mbps, 1.0, "cpu_e2e_device_failed_midrun",
                     note=f"TPU bench failed mid-run ({e!r:.120}); "
                          "value is the native CPU e2e path",
                     cpu_inmem_mbps=round(cpu_inmem),
                     cpu_rebuild_mbps=round(cpu_rebuild),
                     **secondary)
            return
        # correctness failures must NOT fall back to a healthy-looking
        # line: a digest mismatch is data corruption and fails the bench
        if shard_digests(base) != cpu_digests:
            raise AssertionError("TPU shards != native shards")
        log("all 14 shard digests identical to the native path")
        measure_tpu_rebuild(base, dat_size, slab_mb)
        # e2e context block: honest about being tunnel-bounded — the
        # in-run link bound and the probe say WHAT bound it
        e2e_ctx = {"tpu_e2e_mbps": round(tpu_mbps, 1),
                   "cpu_e2e_mbps": round(cpu_mbps, 1),
                   "vs_cpu_e2e": round(tpu_mbps / cpu_mbps, 2),
                   "link_probe_mbps": {"h2d": round(h2d),
                                       "d2h": round(d2h)},
                   "stages": stages,
                   "note": ("bounded by the shared axon tunnel "
                            "(environmental); e2e_vs_link_bound=1.0 "
                            "means the pipeline saturates the link")}
        extras = {"e2e_tunnel": e2e_ctx,
                  "cpu_inmem_mbps": round(cpu_inmem),
                  "cpu_rebuild_mbps": round(cpu_rebuild),
                  "device_init_attempts": retry_log}
        try:
            med, best, thr = measure_device_resident(slab_mb)
            extras["device_percall_mbps"] = round(thr)
            extras["device_percall_note"] = \
                "per-call dispatch over the tunnel (~65ms RTT each); " \
                "see chained_fit for the RTT-free kernel rate"
        except Exception as e:  # noqa: BLE001 - secondary metric only
            log(f"device-resident measurement failed: {e!r}")
        extras.update(secondary_configs(True, chained_by_geo))
        if chained and cpu_inmem:
            emit(chained, chained / cpu_inmem, "device_kernel_chained",
                 chained_fit=chained_diag, **extras)
        else:
            # kernel figure unavailable: the tunnel-bounded e2e is the
            # best remaining device number — marked as such
            emit(tpu_mbps, tpu_mbps / cpu_mbps, "tpu_e2e_tunnel_bound",
                 **extras)
    finally:
        if not config.env_bool("SW_BENCH_KEEP"):
            if user_dir:
                from seaweedfs_tpu.ec import to_ext
                # caller-provided dir may hold unrelated files: remove only
                # what the bench created
                for p in [base + ".dat"] + [
                        base + to_ext(i) for i in range(TOTAL)]:
                    if os.path.exists(p):
                        os.remove(p)
            else:
                shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    # SIGUSR1 dumps all thread stacks to stderr — first diagnostic for
    # a wedged bench run (tunnel stalls, drill deadlocks)
    import faulthandler
    import signal
    faulthandler.register(signal.SIGUSR1)
    if "--cluster-drill" in sys.argv:
        # subprocess mode: BASELINE config 5 under whatever JAX_PLATFORMS
        # / XLA_FLAGS the parent set (virtual CPU mesh), one line out.
        # Re-apply the platform request FIRST: sitecustomize pre-imported
        # jax on the axon platform, and without this the mesh codec's
        # first array touch initializes the TPU tunnel backend — wedging
        # the whole drill when the tunnel is down (r4 failure mode)
        from seaweedfs_tpu.util.jax_platform import honor_platform_request
        honor_platform_request()
        result = measure_cluster_rebuild(
            config.env_int("SW_BENCH_CLUSTER_MB"),
            config.env_int("SW_BENCH_CLUSTER_SERVERS"))
        print("CLUSTER_DRILL " + json.dumps(result), flush=True)
    elif "--dp-crash-server" in sys.argv:
        # crash-drill child: a volume server the parent kill -9s
        # mid-burst (group-commit fsync mode comes in via the env)
        from seaweedfs_tpu.util.jax_platform import honor_platform_request
        honor_platform_request()
        from seaweedfs_tpu.server.volume_server import VolumeServer
        _vs = VolumeServer(
            port=0, directories=[config.env_str("SW_BENCH_DP_DIR")],
            master_url=config.env_str("SW_BENCH_DP_MASTER"),
            pulse_seconds=1, max_volume_counts=[8]).start()
        print("DP_CRASH_READY " + json.dumps(
            {"url": _vs.url, "fast_url": _vs.fast_url}), flush=True)
        signal.pause()
    elif "data_plane" in sys.argv:
        # standalone data-plane bench: the saturation pass plus the
        # durable-mode trial set and the kill -9 crash-consistency drill
        from seaweedfs_tpu.util.jax_platform import honor_platform_request
        honor_platform_request()
        result = measure_data_plane()
        result.update(_jax_provenance())
        print(json.dumps(result), flush=True)
        bench_diff_gate(result, drill="data_plane")
    elif "cluster_scrub_repair" in sys.argv:
        # standalone integrity drill: detection latency, scrub MB/s,
        # scrub overhead on the foreground p99, TTR per incident kind
        from seaweedfs_tpu.util.jax_platform import honor_platform_request
        honor_platform_request()
        result = measure_cluster_scrub_repair()
        result.update(_jax_provenance())
        print(json.dumps(result), flush=True)
        bench_diff_gate(result, drill="cluster_scrub_repair")
    elif "cluster_tiering" in sys.argv:
        # standalone f4 tiering drill: foreground p50/p99 during a
        # rate-capped hot->warm demotion vs healthy, demotion MB/s,
        # zero failed/blocked writes, bit-identical across the flip
        from seaweedfs_tpu.util.jax_platform import honor_platform_request
        honor_platform_request()
        result = measure_cluster_tiering()
        result.update(_jax_provenance())
        print(json.dumps(result), flush=True)
        bench_diff_gate(result, drill="cluster_tiering")
    else:
        main()
