"""collection.* shell commands (reference command_collection_*.go)."""

from __future__ import annotations

from typing import List

from .command_env import CommandEnv, command, parse_flags


@command("collection.list", ": list collections")
def collection_list(env: CommandEnv, args: List[str]):
    names = set()
    for replicas in env.all_volumes().values():
        names.add(replicas[0].get("collection", ""))
    for info in env.ec_volumes().values():
        names.add(info.get("collection", ""))
    for name in sorted(names):
        env.write(f"collection {name!r}")


@command("collection.delete",
         "-collection <name> : delete a collection's volumes")
def collection_delete(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    name = flags.get("collection", "")
    if not name:
        env.write("usage: collection.delete -collection <name>")
        return
    out = env.master_post(f"/col/delete?collection={name}")
    env.write(f"deleted volumes: {out.get('deleted', [])}")
