"""bucket.* shell commands (reference weed/shell/command_bucket_*.go).

Buckets are directories under the filer's buckets folder
(reference filer_buckets.go); these commands ride FilerClient's bucket
API — the same surface the S3 gateway uses.
"""

from __future__ import annotations

from typing import List

from .command_env import CommandEnv, command, parse_flags


@command("bucket.list", ": list buckets")
def bucket_list(env: CommandEnv, args: List[str]):
    entries = env.filer().list_buckets()
    if not entries:
        env.write("no buckets")
        return
    for e in entries:
        env.write(e.name)


@command("bucket.create",
         "-name <bucket> [-collection <c>] : create a bucket")
def bucket_create(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    name = flags.get("name")
    if not name:
        env.write("usage: bucket.create -name <bucket>")
        return
    env.filer().create_bucket(name, collection=flags.get("collection", ""))
    env.write(f"created bucket {name}")


@command("bucket.delete", "-name <bucket> : delete a bucket recursively")
def bucket_delete(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    name = flags.get("name")
    if not name:
        env.write("usage: bucket.delete -name <bucket>")
        return
    env.filer().delete_bucket(name)
    env.write(f"deleted bucket {name}")
