"""CommandEnv — shared state for shell commands (reference
weed/shell/commands.go CommandEnv + MasterClient)."""

from __future__ import annotations

import shlex
from typing import Callable, Dict, List

from ..server.http_util import HttpError, get_json, http_call, post_json

COMMANDS: Dict[str, Callable] = {}
HELP: Dict[str, str] = {}


def command(name: str, help_text: str = ""):
    def deco(fn):
        COMMANDS[name] = fn
        HELP[name] = help_text or (fn.__doc__ or "").strip()
        return fn
    return deco


class CommandEnv:
    def __init__(self, master_url: str, out=None, filer_url: str = ""):
        self.master_url = master_url
        self.filer_url = filer_url
        self.cwd = "/"          # fs.* commands' working directory
        # admin operations move whole volumes (encode/copy/rebuild of
        # tens of GB): a short client deadline would orphan a
        # still-running server-side op, so the cap is generous — the
        # reference's gRPC admin streams carry no deadline at all.
        # Batch drivers (bench) lower it to keep their runs bounded.
        self.admin_timeout = 3600.0
        import sys
        self.out = out or sys.stdout

    def filer(self):
        """FilerClient for fs.* commands (requires shell -filer)."""
        if not self.filer_url:
            raise HttpError(400, "no filer configured: start the shell "
                                 "with -filer <host:port>")
        from ..filer.filer_client import FilerClient
        return FilerClient(self.filer_url)

    def resolve(self, path: str) -> str:
        """Absolute path for an fs.* operand, relative to fs.cd's cwd."""
        import posixpath
        if not path:
            return self.cwd
        if not path.startswith("/"):
            path = posixpath.join(self.cwd, path)
        return posixpath.normpath(path)

    def write(self, *args):
        print(*args, file=self.out)

    # -- cluster state helpers --------------------------------------------
    def master_get(self, path: str) -> dict:
        return get_json(f"http://{self.master_url}{path}")

    def master_post(self, path: str) -> dict:
        return post_json(f"http://{self.master_url}{path}")

    def node_post(self, node: str, path: str,
                  timeout: "float | None" = None,
                  body: dict = None) -> dict:
        if timeout is None:
            timeout = self.admin_timeout
        return post_json(f"http://{node}{path}", body, timeout=timeout)

    def node_get(self, node: str, path: str) -> dict:
        return get_json(f"http://{node}{path}")

    def cluster_nodes(self) -> List[dict]:
        return self.master_get("/cluster/status").get("nodes", [])

    def all_volumes(self) -> Dict[str, List[dict]]:
        return self.master_get("/cluster/volumes").get("volumes", {})

    def ec_volumes(self) -> Dict[str, dict]:
        return self.master_get("/cluster/ec_status").get("volumes", {})


def split_script(script: str) -> List[str]:
    """Split a ';'-separated command script into lines, ignoring
    semicolons inside single/double quotes — shared by `shell -c` and
    the master's maintenance cron."""
    parts, cur, quote = [], [], None
    for ch in script:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
            cur.append(ch)
        elif ch == ";":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


def run_command(env: CommandEnv, line: str) -> bool:
    """Execute one shell line. Returns False on 'exit'."""
    line = line.strip()
    if not line or line.startswith("#"):
        return True
    if line in ("exit", "quit"):
        return False
    try:
        parts = shlex.split(line)
    except ValueError as e:
        # unbalanced quotes must not kill the REPL/script
        env.write(f"error: {e}")
        return True
    name, args = parts[0], parts[1:]
    if name == "help":
        if args and args[0] in HELP:
            env.write(f"{args[0]}: {HELP[args[0]]}")
        else:
            for cmd in sorted(COMMANDS):
                env.write(f"  {cmd:28s} {HELP.get(cmd, '').splitlines()[0] if HELP.get(cmd) else ''}")
        return True
    fn = COMMANDS.get(name)
    if fn is None:
        env.write(f"unknown command {name!r}; try 'help'")
        return True
    try:
        fn(env, args)
    except HttpError as e:
        env.write(f"error: {e.status} {e.message or e}")
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:  # noqa: BLE001 — a REPL must survive any
        env.write(f"error: {type(e).__name__}: {e}")  # command failure
    return True


def parse_flags2(args: List[str], bool_flags=()):
    """Like parse_flags but keeps positional operands and never lets a
    known boolean flag swallow the operand after it.
    '-l /dir' with bool_flags={'l'} -> ({'l': 'true'}, ['/dir'])."""
    flags: Dict[str, str] = {}
    ops: List[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a.startswith("-"):
            key = a.lstrip("-")
            if "=" in key:
                k, v = key.split("=", 1)
                flags[k] = v
            elif key in bool_flags:
                flags[key] = "true"
            elif i + 1 < len(args) and not args[i + 1].startswith("-"):
                flags[key] = args[i + 1]
                i += 1
            else:
                flags[key] = "true"
        else:
            ops.append(a)
        i += 1
    return flags, ops


def parse_flags(args: List[str]) -> Dict[str, str]:
    """'-volumeId 3 -collection x -force' -> {volumeId: 3, ...}."""
    out: Dict[str, str] = {}
    i = 0
    while i < len(args):
        a = args[i]
        if a.startswith("-"):
            key = a.lstrip("-")
            if "=" in key:
                k, v = key.split("=", 1)
                out[k] = v
            elif i + 1 < len(args) and not args[i + 1].startswith("-"):
                out[key] = args[i + 1]
                i += 1
            else:
                out[key] = "true"
        i += 1
    return out
