"""CommandEnv — shared state for shell commands (reference
weed/shell/commands.go CommandEnv + MasterClient)."""

from __future__ import annotations

import shlex
from typing import Callable, Dict, List

from ..server.http_util import HttpError, get_json, http_call, post_json

COMMANDS: Dict[str, Callable] = {}
HELP: Dict[str, str] = {}


def command(name: str, help_text: str = ""):
    def deco(fn):
        COMMANDS[name] = fn
        HELP[name] = help_text or (fn.__doc__ or "").strip()
        return fn
    return deco


class CommandEnv:
    def __init__(self, master_url: str, out=None):
        self.master_url = master_url
        import sys
        self.out = out or sys.stdout

    def write(self, *args):
        print(*args, file=self.out)

    # -- cluster state helpers --------------------------------------------
    def master_get(self, path: str) -> dict:
        return get_json(f"http://{self.master_url}{path}")

    def master_post(self, path: str) -> dict:
        return post_json(f"http://{self.master_url}{path}")

    def node_post(self, node: str, path: str, timeout: float = 600) -> dict:
        return post_json(f"http://{node}{path}", timeout=timeout)

    def node_get(self, node: str, path: str) -> dict:
        return get_json(f"http://{node}{path}")

    def cluster_nodes(self) -> List[dict]:
        return self.master_get("/cluster/status").get("nodes", [])

    def all_volumes(self) -> Dict[str, List[dict]]:
        return self.master_get("/cluster/volumes").get("volumes", {})

    def ec_volumes(self) -> Dict[str, dict]:
        return self.master_get("/cluster/ec_status").get("volumes", {})


def run_command(env: CommandEnv, line: str) -> bool:
    """Execute one shell line. Returns False on 'exit'."""
    line = line.strip()
    if not line or line.startswith("#"):
        return True
    if line in ("exit", "quit"):
        return False
    parts = shlex.split(line)
    name, args = parts[0], parts[1:]
    if name == "help":
        if args and args[0] in HELP:
            env.write(f"{args[0]}: {HELP[args[0]]}")
        else:
            for cmd in sorted(COMMANDS):
                env.write(f"  {cmd:28s} {HELP.get(cmd, '').splitlines()[0] if HELP.get(cmd) else ''}")
        return True
    fn = COMMANDS.get(name)
    if fn is None:
        env.write(f"unknown command {name!r}; try 'help'")
        return True
    try:
        fn(env, args)
    except HttpError as e:
        env.write(f"error: {e.status} {e.message or e}")
    except (ValueError, KeyError) as e:
        env.write(f"error: {type(e).__name__}: {e}")
    return True


def parse_flags(args: List[str]) -> Dict[str, str]:
    """'-volumeId 3 -collection x -force' -> {volumeId: 3, ...}."""
    out: Dict[str, str] = {}
    i = 0
    while i < len(args):
        a = args[i]
        if a.startswith("-"):
            key = a.lstrip("-")
            if "=" in key:
                k, v = key.split("=", 1)
                out[k] = v
            elif i + 1 < len(args) and not args[i + 1].startswith("-"):
                out[key] = args[i + 1]
                i += 1
            else:
                out[key] = "true"
        i += 1
    return out
