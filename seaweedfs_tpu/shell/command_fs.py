"""fs.* shell commands (reference weed/shell/command_fs_*.go): browse
and manipulate the filer namespace, and save/load/notify its metadata."""

from __future__ import annotations

import json
import posixpath
import time
from typing import List

from ..filer.entry import Entry, entry_from_wire, entry_to_wire
from ..server.http_util import HttpError, http_call
from .command_env import CommandEnv, command, parse_flags2


def _list_all(client, path: str):
    """Every entry of a directory, paginating past the server's batch
    limit — a silent cap here would truncate fs.meta.save backups and
    fs.rm -r."""
    start = ""
    while True:
        batch = client.list_entries(path, start_file=start, limit=1000)
        yield from batch
        if len(batch) < 1000:
            return
        start = batch[-1].name


def _walk(client, path: str):
    """Yield entries depth-first under path (path's own entry first if
    it exists and is not the root)."""
    from ..filer.filer import NotFoundError
    if path != "/":
        try:
            e = client.find_entry(path)
        except (HttpError, NotFoundError):
            return
        yield e
        if not e.is_directory:
            return
    for e in _list_all(client, path):
        if e.is_directory:
            yield from _walk(client, e.full_path)
        else:
            yield e


@command("fs.cd", "<dir> : change the fs.* working directory")
def fs_cd(env: CommandEnv, args: List[str]):
    path = env.resolve(args[0] if args else "/")
    if path != "/":
        e = env.filer().find_entry(path)
        if not e.is_directory:
            env.write(f"{path} is not a directory")
            return
    env.cwd = path


@command("fs.pwd", ": print the fs.* working directory")
def fs_pwd(env: CommandEnv, args: List[str]):
    env.write(env.cwd)


@command("fs.ls", "[-l] [path] : list a filer directory")
def fs_ls(env: CommandEnv, args: List[str]):
    flags, ops = parse_flags2(args, bool_flags={"l"})
    long = bool(flags.get("l"))
    path = env.resolve(ops[0] if ops else "")
    entries = list(_list_all(env.filer(), path))
    for e in sorted(entries, key=lambda x: x.full_path):
        name = e.name + ("/" if e.is_directory else "")
        if long:
            mtime = time.strftime("%Y-%m-%d %H:%M",
                                  time.localtime(e.attr.mtime))
            env.write(f"{e.attr.mode:o} {e.size():>12} {mtime} {name}")
        else:
            env.write(name)


@command("fs.cat", "<path> : print file content")
def fs_cat(env: CommandEnv, args: List[str]):
    if not args:
        env.write("usage: fs.cat <path>")
        return
    env.filer()        # same no-filer-configured guard as other fs.*
    import urllib.parse
    path = urllib.parse.quote(env.resolve(args[0]))
    data = http_call("GET", f"http://{env.filer_url}{path}")
    try:
        env.write(data.decode())
    except UnicodeDecodeError:
        env.write(f"<{len(data)} binary bytes>")


@command("fs.du", "[path] : disk usage per directory subtree")
def fs_du(env: CommandEnv, args: List[str]):
    path = env.resolve(args[0] if args else "")
    client = env.filer()
    total_bytes = total_files = 0
    for e in _walk(client, path):
        if not e.is_directory:
            total_bytes += e.size()
            total_files += 1
    env.write(f"{total_bytes} bytes\t{total_files} files\t{path}")


@command("fs.tree", "[path] : recursive listing")
def fs_tree(env: CommandEnv, args: List[str]):
    path = env.resolve(args[0] if args else "")
    client = env.filer()
    root_depth = path.rstrip("/").count("/")
    count = 0
    for e in _walk(client, path):
        depth = e.full_path.count("/") - root_depth
        indent = "  " * max(depth, 0)
        suffix = "/" if e.is_directory else f" ({e.size()})"
        env.write(f"{indent}{e.name}{suffix}")
        count += 1
    env.write(f"{count} entries")


@command("fs.mkdir", "<dir> : create a directory")
def fs_mkdir(env: CommandEnv, args: List[str]):
    if not args:
        env.write("usage: fs.mkdir <dir>")
        return
    env.filer().mkdir(env.resolve(args[0]))


@command("fs.mv", "<src> <dst> : move/rename a file or directory")
def fs_mv(env: CommandEnv, args: List[str]):
    if len(args) != 2:
        env.write("usage: fs.mv <src> <dst>")
        return
    src, dst = env.resolve(args[0]), env.resolve(args[1])
    env.filer().rename_entry(src, dst)
    env.write(f"{src} -> {dst}")


@command("fs.rm", "[-r] <path> : delete a file or directory")
def fs_rm(env: CommandEnv, args: List[str]):
    flags, operands = parse_flags2(args, bool_flags={"r"})
    if not operands:
        env.write("usage: fs.rm [-r] <path>")
        return
    for p in operands:
        env.filer().delete_entry(env.resolve(p),
                                 recursive=bool(flags.get("r")),
                                 ignore_recursive_error=False)


@command("fs.meta.cat", "<path> : print one entry's raw metadata")
def fs_meta_cat(env: CommandEnv, args: List[str]):
    """Reference command_fs_meta_cat.go: the full wire-shape entry
    (attrs, chunks, extended) as indented JSON."""
    from ..filer.filer import NotFoundError
    _flags, operands = parse_flags2(args)
    if not operands:
        env.write("usage: fs.meta.cat <path>")
        return
    path = env.resolve(operands[0])
    try:
        e = env.filer().find_entry(path)
    except (HttpError, NotFoundError):
        env.write(f"{path}: not found")
        return
    env.write(json.dumps(entry_to_wire(e), indent=2, sort_keys=True))


@command("fs.meta.save",
         "[-o out.jsonl] [path] : dump filer metadata to a file")
def fs_meta_save(env: CommandEnv, args: List[str]):
    flags, operands = parse_flags2(args)
    path = env.resolve(operands[0] if operands else "")
    out_path = flags.get("o") or \
        f"{(path.strip('/') or 'root').replace('/', '-')}-" \
        f"{time.strftime('%Y-%m-%d-%H-%M')}.meta.jsonl"
    client = env.filer()
    count = 0
    with open(out_path, "w") as f:
        for e in _walk(client, path):
            f.write(json.dumps(entry_to_wire(e),
                               separators=(",", ":")) + "\n")
            count += 1
    env.write(f"saved {count} entries to {out_path}")


@command("fs.meta.load", "-i <in.jsonl> : recreate filer metadata")
def fs_meta_load(env: CommandEnv, args: List[str]):
    flags, operands = parse_flags2(args)
    in_path = flags.get("i") or (operands[0] if operands else "")
    if not in_path:
        env.write("usage: fs.meta.load -i <in.jsonl>")
        return
    client = env.filer()
    count = 0
    with open(in_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            # directories restore through the same create/update path
            # as files so their saved attrs (mode, mtime, owner)
            # survive the round trip
            entry = entry_from_wire(json.loads(line))
            try:
                client.create_entry(entry)
            except HttpError as e:
                if e.status != 409:
                    raise
                client.update_entry(entry)
            count += 1
    env.write(f"loaded {count} entries")


@command("fs.meta.notify",
         "[path] : re-emit metadata events for every entry (replays the "
         "subtree into the event log for subscribers/replicators)")
def fs_meta_notify(env: CommandEnv, args: List[str]):
    path = env.resolve(args[0] if args else "")
    client = env.filer()
    count = 0
    for e in _walk(client, path):
        client.update_entry(e)     # same-content update -> event
        count += 1
    env.write(f"notified {count} entries")
