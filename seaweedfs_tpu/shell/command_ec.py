"""ec.* shell commands — the north-star orchestration.

Reference weed/shell/command_ec_encode.go / _rebuild.go / _decode.go /
_balance.go: freeze -> generate -> spread -> mount -> drop originals;
rebuild lost shards on the freest node; decode back to normal volumes;
balance shards across nodes.
"""

from __future__ import annotations

from typing import Dict, List

from ..ec.constants import DATA_SHARDS, TOTAL_SHARDS
from ..server.http_util import HttpError
from .command_env import CommandEnv, command, parse_flags


def _free_nodes(env: CommandEnv) -> List[dict]:
    return sorted(env.cluster_nodes(), key=lambda n: -n.get("free", 0))


def _volume_replicas(env: CommandEnv, vid: int) -> List[dict]:
    return env.all_volumes().get(str(vid), [])


def balanced_ec_distribution(nodes: List[dict]) -> List[str]:
    """Assign 14 shards round-robin by free slots (reference
    balancedEcDistribution command_ec_encode.go:237-253)."""
    if not nodes:
        raise ValueError("no volume servers")
    # plain round-robin over servers that still have free EC slots (one
    # volume slot = 10 shard slots)
    picked: Dict[str, int] = {n["url"]: 0 for n in nodes}
    free_slots = {n["url"]: max(n.get("free", 0), 0) * 10 for n in nodes}
    urls = [n["url"] for n in nodes]
    out: List[str] = []
    i = 0
    spins = 0
    while len(out) < TOTAL_SHARDS:
        url = urls[i % len(urls)]
        i += 1
        if free_slots[url] - picked[url] >= 1:
            out.append(url)
            picked[url] += 1
            spins = 0
        else:
            spins += 1
            if spins > len(urls):
                raise ValueError("not enough free EC slots in the cluster")
    return out


def collect_volume_ids_for_ec_encode(env: CommandEnv, collection: str,
                                     full_percent: float = 0.95,
                                     quiet_seconds: float = 3600,
                                     size_limit: int = None) -> List[int]:
    """Quiet & nearly-full volumes (reference
    collectVolumeIdsForEcEncode command_ec_encode.go:255-287)."""
    import time
    if size_limit is None:
        status = env.master_get("/dir/status")
        size_limit = status.get("volumeSizeLimit") \
            or 30 * 1024 * 1024 * 1024
    now = time.time()
    out = []
    for vid_s, replicas in env.all_volumes().items():
        vi = replicas[0]
        if vi.get("collection", "") != collection:
            continue
        if vi.get("size", 0) < full_percent * size_limit:
            continue
        modified = vi.get("modified_at", 0)
        if modified and now - modified < quiet_seconds:
            continue
        out.append(int(vid_s))
    return out


@command("ec.encode",
         "-volumeId <id> | -collection <name> [-fullPercent 0.95] "
         "[-mode stream|copy] : erasure-code volumes and spread 14 "
         "shards across the cluster (stream = push shard ranges to "
         "holders while later slabs encode; copy = legacy "
         "generate-then-pull)")
def ec_encode(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    if "volumeId" in flags:
        vids = [int(flags["volumeId"])]
    elif "collection" in flags:
        vids = collect_volume_ids_for_ec_encode(
            env, flags["collection"], float(flags.get("fullPercent", 0.95)),
            quiet_seconds=float(flags.get("quietFor", 3600)))
    else:
        env.write("usage: ec.encode -volumeId <id> | -collection <name>")
        return
    for vid in vids:
        do_ec_encode(env, vid, mode=flags.get("mode"))


def do_ec_encode(env: CommandEnv, vid: int, mode: str = None,
                 timings: Dict = None, rate_mbps: float = 0.0):
    """Freeze -> encode+spread -> mount -> drop originals.

    mode: "stream" (default; `SW_EC_SPREAD_MODE` overrides) sends the
    shard assignment to the source, which pushes each shard's slab
    ranges to its holder WHILE later slabs encode — remote-bound shards
    never touch the source disk. "copy" is the legacy two-phase flow
    (all 14 shards land on the source, then targets pull whole files);
    stream mode also falls back to it when the source predates the
    streaming endpoint or the spread dies mid-shard.

    Any failure after the freeze unwinds: generated shard files (and
    ``.part`` stages) are deleted cluster-wide and each replica's
    readonly flag is restored to its own prior state — a failed encode
    must not leave the volume frozen with orphan shards.

    ``timings``, when given, records encode/spread busy seconds,
    ``overlap_frac``, and the spread counters for bench. ``rate_mbps``
    > 0 paces the streaming spread (the tierer's background cap);
    copy mode ignores it."""
    from ..util import config as _config
    from ..util import tracing
    mode = (mode or _config.env_str("SW_EC_SPREAD_MODE") or
            "stream").lower()
    replicas = _volume_replicas(env, vid)
    if not replicas:
        env.write(f"volume {vid} not found")
        return
    collection = replicas[0].get("collection", "")
    source = replicas[0]["url"]
    root = tracing.start_span("ec.encode", volume=vid, mode=mode)
    if timings is not None:
        timings["mode"] = mode
    try:
        # 1. freeze every replica, recording each holder's OWN prior
        # state (not the master's heartbeat-delayed view) so a failure
        # thaws exactly what this command froze
        froze: List[str] = []
        for r in replicas:
            out = env.node_post(r["url"],
                                f"/admin/volume/readonly?volume={vid}")
            if not (out or {}).get("was_readonly"):
                froze.append(r["url"])
        assignment = balanced_ec_distribution(_free_nodes(env))
        by_node: Dict[str, List[int]] = {}
        for sid, url in enumerate(assignment):
            by_node.setdefault(url, []).append(sid)
        try:
            # 2+3. encode + spread + mount
            if mode == "copy":
                _encode_spread_copy(env, vid, collection, source,
                                    by_node, timings)
            else:
                try:
                    _encode_spread_streaming(env, vid, collection,
                                             source, assignment,
                                             timings, rate_mbps)
                except HttpError as e:
                    env.write(f"volume {vid}: streaming encode failed "
                              f"({e.status}); falling back to copy mode")
                    root.tags["fallback"] = "copy"
                    _cleanup_partial_encode(env, vid, collection,
                                            set(assignment) | {source})
                    _encode_spread_copy(env, vid, collection, source,
                                        by_node, timings)
        except BaseException as e:
            _cleanup_partial_encode(env, vid, collection,
                                    set(assignment) | {source})
            for url in froze:
                try:
                    env.node_post(url,
                                  f"/admin/volume/readonly?volume={vid}"
                                  f"&readonly=false")
                except HttpError:
                    pass
            root.tags.setdefault("error", type(e).__name__)
            raise
        # 5. drop the original volume everywhere
        for r in replicas:
            env.node_post(r["url"], f"/admin/delete_volume?volume={vid}")
        if timings is not None:
            timings["trace_id"] = root.trace_id
    finally:
        tracing.finish_span(root)
    env.write(f"volume {vid}: ec encoded, original removed")


def _cleanup_partial_encode(env: CommandEnv, vid: int, collection: str,
                            nodes):
    """Best-effort removal of every shard file and ``.part`` stage a
    failed encode may have left on any involved node."""
    all_shards = ",".join(map(str, range(TOTAL_SHARDS)))
    for url in nodes:
        try:
            env.node_post(url, f"/admin/ec/delete_shards?volume={vid}"
                               f"&collection={collection}"
                               f"&shards={all_shards}")
        except HttpError:
            pass


def _encode_spread_streaming(env: CommandEnv, vid: int, collection: str,
                             source: str, assignment: List[str],
                             timings: Dict = None,
                             rate_mbps: float = 0.0):
    """One POST: the source encodes and pushes each shard's slab ranges
    to its assigned holder while later slabs encode. Afterwards only
    the KB-scale index sidecars (.ecx/.vif) are copied to remote
    holders, then every holder mounts its shards."""
    import time as _time
    from ..util.fanout import fan_out_must_succeed
    spares = [n["url"] for n in _free_nodes(env)
              if n["url"] not in assignment]
    t0 = _time.perf_counter()
    out = env.node_post(
        source, f"/admin/ec/generate?volume={vid}"
                f"&collection={collection}",
        body={"assignment": {str(s): u
                             for s, u in enumerate(assignment)},
              "spares": spares,
              "rate_mbps": rate_mbps})
    wall = _time.perf_counter() - t0
    stats = out.get("stats") or {}
    # re-group by the FINAL placement: failover may have moved a dead
    # target's shards to a spare ('' = the source kept them)
    final = {int(s): (u or source)
             for s, u in (out.get("assignment") or {}).items()}
    if not final:
        final = dict(enumerate(assignment))
    by_node: Dict[str, List[int]] = {}
    for sid in sorted(final):
        by_node.setdefault(final[sid], []).append(sid)
    env.write(f"volume {vid}: streamed {len(final)} shards from "
              f"{source} (encode {stats.get('encode_busy_s', 0.0)}s ∥ "
              f"spread {stats.get('spread_busy_s', 0.0)}s, overlap "
              f"{stats.get('overlap_frac', 0.0)})")

    def mount(target):
        url, shards = target
        s = ",".join(map(str, shards))
        if url != source:
            # shard bytes are already there — pull only the sidecars
            env.node_post(url, f"/admin/ec/copy?volume={vid}"
                               f"&collection={collection}"
                               f"&source={source}&shards="
                               f"&copy_ecx=true")
        env.node_post(url, f"/admin/ec/mount?volume={vid}"
                           f"&collection={collection}&shards={s}")
        return s

    for (url, _), s in zip(
            by_node.items(),
            fan_out_must_succeed(mount, list(by_node.items()),
                                 what=f"ec shard mount for volume {vid}",
                                 dedicated=True)):
        env.write(f"volume {vid}: shards {s} -> {url}")
    if source not in by_node:
        # the source kept no shards: drop its now-orphan index sidecars
        env.node_post(source, f"/admin/ec/delete_shards?volume={vid}"
                              f"&collection={collection}&shards=")
    if timings is not None:
        timings["encode_wall_s"] = \
            timings.get("encode_wall_s", 0) + wall
        _merge_rebuild_stats(timings, out)


def _encode_spread_copy(env: CommandEnv, vid: int, collection: str,
                        source: str, by_node: Dict[str, List[int]],
                        timings: Dict = None):
    """Legacy two-phase flow: generate all 14 shards on the source,
    then every target pulls + mounts its shards concurrently (reference
    parallelCopyEcShardsFromSource, command_ec_encode.go:200-235:
    goroutine per target server)."""
    import time as _time
    from ..util.fanout import fan_out_must_succeed
    t0 = _time.perf_counter()
    env.node_post(source, f"/admin/ec/generate?volume={vid}"
                          f"&collection={collection}")
    t1 = _time.perf_counter()
    env.write(f"volume {vid}: generated {TOTAL_SHARDS} shards on "
              f"{source}")

    def spread(target):
        url, shards = target
        s = ",".join(map(str, shards))
        if url != source:
            env.node_post(url, f"/admin/ec/copy?volume={vid}"
                               f"&collection={collection}&source={source}"
                               f"&shards={s}")
        env.node_post(url, f"/admin/ec/mount?volume={vid}"
                           f"&collection={collection}&shards={s}")
        return s

    for (url, _), s in zip(
            by_node.items(),
            fan_out_must_succeed(spread, list(by_node.items()),
                                 what=f"ec shard spread for volume {vid}",
                                 dedicated=True)):
        env.write(f"volume {vid}: shards {s} -> {url}")
    # 4. delete source's unassigned shard files
    source_keeps = set(by_node.get(source, []))
    extra = [s for s in range(TOTAL_SHARDS) if s not in source_keeps]
    if extra:
        env.node_post(source, f"/admin/ec/delete_shards?volume={vid}"
                              f"&collection={collection}"
                              f"&shards={','.join(map(str, extra))}")
    t2 = _time.perf_counter()
    if timings is not None:
        timings["encode_busy_s"] = \
            timings.get("encode_busy_s", 0) + (t1 - t0)
        timings["spread_busy_s"] = \
            timings.get("spread_busy_s", 0) + (t2 - t1)
        timings["encode_wall_s"] = \
            timings.get("encode_wall_s", 0) + (t2 - t0)
        timings.setdefault("overlap_frac", 0.0)


@command("ec.rebuild",
         "[-collection <name>] [-mode stream|copy] "
         "[-repair auto|trace|piggyback|full] : regenerate missing "
         "shards (stream = ranged survivor gather overlapped with the "
         "decode; copy = legacy whole-shard copies; repair = "
         "single-shard strategy — trace ships projected sub-shard "
         "symbols from all survivors on flat volumes, piggyback ships "
         "half-shard planes on piggyback-layout volumes, full pulls k "
         "whole ranges, auto picks by the volume's layout)")
def ec_rebuild(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    for vid_s, info in env.ec_volumes().items():
        vid = int(vid_s)
        collection = info.get("collection", "")
        if "collection" in flags and collection != flags["collection"]:
            continue
        shards = {int(s): urls for s, urls in info["shards"].items()}
        missing = [s for s in range(TOTAL_SHARDS) if s not in shards]
        if not missing:
            continue
        if len(shards) < DATA_SHARDS:
            env.write(f"volume {vid}: only {len(shards)} shards left, "
                      f"cannot rebuild")
            continue
        do_ec_rebuild(env, vid, collection, shards, missing,
                      mode=flags.get("mode"),
                      repair=flags.get("repair"))


def _merge_rebuild_stats(timings: Dict, out: dict):
    """Fold the rebuilder's stats dict into the shell timings: numbers
    sum across volumes, dict-valued breakdowns (per-phase seconds,
    per-holder fetch/error counts) merge per key."""
    for key, val in (out.get("stats") or {}).items():
        if key == "phases" and isinstance(val, dict):
            agg = timings.setdefault("phases", {})
            for ph, secs in val.items():
                agg[ph] = round(agg.get(ph, 0.0) + secs, 6)
        elif key in ("holder_fetches", "holder_errors") and \
                isinstance(val, dict):
            agg = timings.setdefault(key, {})
            for holder, n in val.items():
                agg[holder] = agg.get(holder, 0) + n
        elif isinstance(val, (int, float)):
            timings[key] = timings.get(key, 0) + val
        else:
            timings[key] = val


def do_ec_rebuild(env: CommandEnv, vid: int, collection: str,
                  shards: Dict[int, List[str]], missing: List[int],
                  timings: Dict[str, float] = None, mode: str = None,
                  repair: str = None):
    """`timings`, when given, records the phase walls plus the
    rebuilder's stats (gather/compute busy time, overlap_frac, dispatch
    telemetry) — the benchmark's overlap accounting.

    mode: "stream" (default; `SW_EC_GATHER_MODE` overrides) pushes the
    survivor holder map to the rebuilder, which pulls slab ranges and
    decodes them overlapped — no whole-shard temp copies, no trailing
    delete_shards pass. "copy" is the legacy copy-then-rebuild flow;
    stream mode also falls back to it if the rebuilder predates the
    streaming endpoint.

    repair: "auto" (default; `SW_EC_REPAIR_MODE` overrides) lets the
    rebuilder pick the cheapest single-shard strategy for the volume's
    layout — trace repair (projected sub-shard symbols from all
    survivors) on flat volumes, plane repair (half-shard planes from
    k+1 helpers) on piggyback volumes. "trace"/"piggyback" force the
    matching strategy and error on the other layout; "full" forces the
    k-survivor gather on either. Stream mode only."""
    from ..util import config as _config
    from ..util import tracing
    mode = (mode or _config.env_str("SW_EC_GATHER_MODE") or
            "stream").lower()
    repair = (repair or _config.env_str("SW_EC_REPAIR_MODE") or
              "auto").lower()
    # shell-side trace root: every call below — survivor gathering, the
    # rebuild, mount — carries its traceparent: ONE trace per operation
    root = tracing.start_span("ec.rebuild", volume=vid, mode=mode,
                              repair=repair)
    try:
        # pick the node with most free slots as rebuilder (reference
        # command_ec_rebuild.go: pick by free slot count)
        rebuilder = _free_nodes(env)[0]["url"]
        if mode == "copy":
            rebuilt = _rebuild_via_copy(env, vid, collection, shards,
                                        rebuilder, root, timings)
        else:
            try:
                rebuilt = _rebuild_streaming(env, vid, collection,
                                             shards, rebuilder, root,
                                             timings, repair=repair)
            except HttpError as e:
                env.write(f"volume {vid}: streaming rebuild failed "
                          f"({e.status}); falling back to copy mode")
                root.tags["fallback"] = "copy"
                rebuilt = _rebuild_via_copy(env, vid, collection,
                                            shards, rebuilder, root,
                                            timings)
        if timings is not None:
            timings["trace_id"] = root.trace_id
    except BaseException as e:
        root.tags.setdefault("error", type(e).__name__)
        raise
    finally:
        tracing.finish_span(root)
    env.write(f"volume {vid}: rebuilt shards {rebuilt} on {rebuilder}")


def _rebuild_streaming(env: CommandEnv, vid: int, collection: str,
                       shards: Dict[int, List[str]], rebuilder: str,
                       root, timings: Dict = None,
                       repair: str = "auto") -> List[int]:
    """One POST: the rebuilder pulls slab-aligned survivor ranges from
    the holder map and feeds them straight into the pipelined decode
    (or, single-shard loss with ``repair`` auto/trace/piggyback, pulls
    projected repair symbols or half-shard planes from the helpers the
    volume's layout prescribes)."""
    import time as _time
    sources = {str(sid): urls for sid, urls in shards.items()
               if rebuilder not in urls}
    t0 = _time.perf_counter()
    out = env.node_post(
        rebuilder,
        f"/admin/ec/rebuild?volume={vid}&collection={collection}",
        body={"sources": sources, "repair": repair})
    t1 = _time.perf_counter()
    rebuilt = out.get("rebuilt", [])
    if timings is not None:
        stats = out.get("stats") or {}
        # stream mode has no serialized gather wall: report the busy
        # times so gather_s + compute_s estimates the SERIALIZED cost
        # the overlap saved (wall_s carries the actual elapsed time)
        timings["gather_s"] = timings.get("gather_s", 0) + \
            stats.get("gather_busy_s", 0.0)
        timings["compute_s"] = timings.get("compute_s", 0) + \
            stats.get("compute_busy_s", 0.0)
        timings["wall_s"] = timings.get("wall_s", 0) + (t1 - t0)
        timings["gathered_shards"] = \
            timings.get("gathered_shards", 0) + \
            stats.get("gather_remote_shards", len(sources))
        _merge_rebuild_stats(timings, out)
    if rebuilt:
        t3 = _time.perf_counter()
        env.node_post(rebuilder,
                      f"/admin/ec/mount?volume={vid}"
                      f"&collection={collection}"
                      f"&shards={','.join(map(str, rebuilt))}")
        if timings is not None:
            timings["mount_s"] = timings.get("mount_s", 0) + \
                (_time.perf_counter() - t3)
    return rebuilt


def _rebuild_via_copy(env: CommandEnv, vid: int, collection: str,
                      shards: Dict[int, List[str]], rebuilder: str,
                      root, timings: Dict = None) -> List[int]:
    """Legacy flow: copy every survivor whole, rebuild locally, delete
    the temp copies."""
    import time as _time
    from ..util import tracing
    from ..util.fanout import fan_out_must_succeed
    local = {s for s, urls in shards.items() if rebuilder in urls}
    # copy surviving shards the rebuilder lacks — pulls from distinct
    # sources run concurrently (reference prepareDataToRecover +
    # goroutine fan-out); the .ecx rides along with exactly one copy
    to_copy = [(sid, urls[0]) for sid, urls in shards.items()
               if sid not in local]
    copied = [sid for sid, _ in to_copy]

    def pull(job):
        (sid, src), with_ecx = job
        # fan-out worker threads don't inherit the contextvar —
        # parent each per-source gather span on the root explicitly
        with tracing.span("gather", parent=root, shard=sid,
                          source=src):
            env.node_post(
                rebuilder,
                f"/admin/ec/copy?volume={vid}&collection={collection}"
                f"&source={src}&shards={sid}"
                f"&copy_ecx={'true' if with_ecx else 'false'}")

    jobs = [(item, (not local) and i == 0)
            for i, item in enumerate(to_copy)]
    t0 = _time.perf_counter()
    fan_out_must_succeed(pull, jobs,
                         what=f"survivor shard copy for volume {vid}",
                         dedicated=True)
    t1 = _time.perf_counter()
    # rebuild + mount only the previously-missing shards
    out = env.node_post(rebuilder,
                        f"/admin/ec/rebuild?volume={vid}"
                        f"&collection={collection}")
    t2 = _time.perf_counter()
    if timings is not None:
        timings["gather_s"] = timings.get("gather_s", 0) + (t1 - t0)
        timings["compute_s"] = timings.get("compute_s", 0) + (t2 - t1)
        timings["wall_s"] = timings.get("wall_s", 0) + (t2 - t0)
        timings["gathered_shards"] = \
            timings.get("gathered_shards", 0) + len(to_copy)
        _merge_rebuild_stats(timings, out)
    rebuilt = out.get("rebuilt", [])
    if rebuilt:
        t3 = _time.perf_counter()
        env.node_post(rebuilder,
                      f"/admin/ec/mount?volume={vid}"
                      f"&collection={collection}"
                      f"&shards={','.join(map(str, rebuilt))}")
        if timings is not None:
            timings["mount_s"] = timings.get("mount_s", 0) + \
                (_time.perf_counter() - t3)
    # clean up temp survivor copies (not mounted here)
    if copied:
        env.node_post(rebuilder,
                      f"/admin/ec/delete_shards?volume={vid}"
                      f"&collection={collection}"
                      f"&shards={','.join(map(str, copied))}")
    return rebuilt


@command("ec.decode",
         "-volumeId <id> | -collection <name> : decode EC back to volumes")
def ec_decode(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    for vid_s, info in env.ec_volumes().items():
        vid = int(vid_s)
        collection = info.get("collection", "")
        if "volumeId" in flags and vid != int(flags["volumeId"]):
            continue
        if "collection" in flags and collection != flags["collection"]:
            continue
        shards = {int(s): urls for s, urls in info["shards"].items()}
        data_shards = {s: u for s, u in shards.items() if s < DATA_SHARDS}
        if len(data_shards) < DATA_SHARDS:
            env.write(f"volume {vid}: missing data shards; run ec.rebuild "
                      f"first")
            continue
        # pick the node holding the most data shards as the decode target
        counts: Dict[str, int] = {}
        for sid, urls in data_shards.items():
            for u in urls:
                counts[u] = counts.get(u, 0) + 1
        target = max(counts, key=counts.get)
        held = {s for s, urls in shards.items() if target in urls}
        for sid, urls in data_shards.items():
            if sid in held:
                continue
            env.node_post(target,
                          f"/admin/ec/copy?volume={vid}"
                          f"&collection={collection}&source={urls[0]}"
                          f"&shards={sid}&copy_ecx=false")
        env.node_post(target, f"/admin/ec/mount?volume={vid}"
                              f"&collection={collection}"
                              f"&shards="
                              f"{','.join(str(s) for s in range(DATA_SHARDS))}")
        env.node_post(target, f"/admin/ec/to_volume?volume={vid}"
                              f"&collection={collection}")
        # remove EC shards cluster-wide
        all_shards = ",".join(map(str, range(TOTAL_SHARDS)))
        holders = {u for urls in shards.values() for u in urls} | {target}
        for u in holders:
            env.node_post(u, f"/admin/ec/delete_shards?volume={vid}"
                             f"&collection={collection}&shards={all_shards}")
        env.write(f"volume {vid}: decoded back to a normal volume on "
                  f"{target}")


def _move_shard(env: CommandEnv, vid: int, collection: str, sid: int,
                src: str, dst: str):
    env.node_post(dst, f"/admin/ec/copy?volume={vid}"
                       f"&collection={collection}&source={src}"
                       f"&shards={sid}")
    env.node_post(dst, f"/admin/ec/mount?volume={vid}"
                       f"&collection={collection}&shards={sid}")
    env.node_post(src, f"/admin/ec/delete_shards?volume={vid}"
                       f"&collection={collection}&shards={sid}")


def _balance_one_ec_volume(env: CommandEnv, vid: int, collection: str,
                           shards: Dict[int, List[str]],
                           node_rack: Dict[str, str]) -> int:
    """Rack-aware two-phase balance of one EC volume (reference
    command_ec_balance.go): first spread shards evenly across RACKS (a
    lost rack must never cost more than its fair share of shards), then
    even node counts within each rack. Returns moves made."""
    import math
    moves = 0
    racks = sorted(set(node_rack.values()))
    nodes_in_rack = {r: sorted(u for u, rr in node_rack.items()
                               if rr == r) for r in racks}

    # replicated shards count EVERY holder (a shard may briefly — or by
    # policy — live on several nodes); a move relocates one replica and
    # must never target a node already holding the shard
    def rack_counts() -> Dict[str, int]:
        c = {r: 0 for r in racks}
        for sid, urls in shards.items():
            for u in urls:
                r = node_rack.get(u)
                if r is not None:
                    c[r] += 1
        return c

    def node_counts(urls) -> Dict[str, int]:
        c = {u: 0 for u in urls}
        for sid, holders in shards.items():
            for h in holders:
                if h in c:
                    c[h] += 1
        return c

    def relocate(sid: int, src: str, dst: str):
        _move_shard(env, vid, collection, sid, src, dst)
        shards[sid] = [dst if u == src else u for u in shards[sid]]

    # phase 1: across racks
    if len(racks) > 1:
        ceil_per_rack = math.ceil(len(shards) / len(racks))
        while True:
            rc = rack_counts()
            hi = max(racks, key=lambda r: rc[r])
            lo = min(racks, key=lambda r: rc[r])
            if rc[hi] <= ceil_per_rack or rc[hi] - rc[lo] <= 1:
                break
            nc = node_counts(nodes_in_rack[lo])
            job = None
            for s in sorted(shards):
                src = next((u for u in shards[s]
                            if node_rack.get(u) == hi), None)
                if src is None:
                    continue
                # racks already holding ANOTHER replica of s (besides
                # the one being moved) are off limits — two replicas of
                # one shard in a rack is exactly the fault-domain
                # collapse this phase exists to prevent
                other_racks = {node_rack.get(u) for u in shards[s]
                               if u != src}
                if lo in other_racks:
                    continue
                dst = min((u for u in nodes_in_rack[lo]
                           if u not in shards[s]),
                          key=lambda u: nc[u], default=None)
                if dst is not None:
                    job = (s, src, dst)
                    break
            if job is None:
                break  # nothing movable without double-placing a shard
            relocate(*job)
            moves += 1

    # phase 2: within each rack
    for r in racks:
        urls = nodes_in_rack[r]
        if len(urls) < 2:
            continue
        while True:
            nc = node_counts(urls)
            hi = max(urls, key=lambda u: nc[u])
            lo = min(urls, key=lambda u: nc[u])
            if nc[hi] - nc[lo] <= 1:
                break
            sid = next((s for s in sorted(shards)
                        if hi in shards[s] and lo not in shards[s]),
                       None)
            if sid is None:
                break
            relocate(sid, hi, lo)
            moves += 1
    return moves


@command("ec.balance",
         "[-collection <name>] : spread EC shards evenly across racks, "
         "then across nodes within each rack")
def ec_balance(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    cluster = env.cluster_nodes()
    if not cluster:
        env.write("no volume servers")
        return
    node_rack = {n["url"]: n.get("rack", "") or "DefaultRack"
                 for n in cluster}
    moves = 0
    for vid_s, info in env.ec_volumes().items():
        vid = int(vid_s)
        collection = info.get("collection", "")
        if "collection" in flags and collection != flags["collection"]:
            continue
        shards = {int(s): list(urls)
                  for s, urls in info["shards"].items()}
        moves += _balance_one_ec_volume(env, vid, collection, shards,
                                        node_rack)
    env.write(f"ec.balance: {moves} shard moves")


@command("volume.ec.degraded",
         ": per-server degraded-read engine status (reconstruct-on-read "
         "batching, slab cache, survivor traffic)")
def volume_ec_degraded(env: CommandEnv, args: List[str]):
    nodes = env.cluster_nodes()
    if not nodes:
        env.write("no volume servers")
        return
    for node in nodes:
        url = node["url"]
        try:
            snap = env.node_get(url, "/status").get("ec_degraded") or {}
        except HttpError as e:
            env.write(f"{url}  unreachable: {e}")
            continue
        reads = int(snap.get("reads", 0))
        batches = int(snap.get("batches", 0))
        coalesced = int(snap.get("batched_requests", 0))
        avg_w = coalesced / batches if batches else 0.0
        env.write(
            f"{url}  reads={reads} batches={batches} "
            f"width(avg/max)={avg_w:.1f}/{int(snap.get('max_batch_requests', 0))} "
            f"hit_ratio={snap.get('cache_hit_ratio', 0.0):.2f} "
            f"cache={int(snap.get('cache_bytes', 0)) >> 10}KB/"
            f"{int(snap.get('cache_entries', 0))} slabs "
            f"survivor={int(snap.get('survivor_bytes', 0)) >> 10}KB "
            f"(remote {int(snap.get('remote_bytes', 0)) >> 10}KB) "
            f"dispatch(host/dev)={int(snap.get('host_dispatches', 0))}/"
            f"{int(snap.get('device_dispatches', 0))} "
            f"p99={snap.get('p99_ms', 0.0):.1f}ms "
            f"errors={int(snap.get('errors', 0))}")


@command("volume.ec.scrub",
         "[-trigger] [-volumeId <id>]: per-server syndrome-scrub status "
         "(passes, bytes verified, corruption found); -trigger runs a "
         "synchronous pass on every server first")
def volume_ec_scrub(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    nodes = env.cluster_nodes()
    if not nodes:
        env.write("no volume servers")
        return
    vid = flags.get("volumeId")
    for node in nodes:
        url = node["url"]
        try:
            if "trigger" in flags:
                q = f"?volume={int(vid)}" if vid else ""
                env.node_post(url, f"/admin/ec/scrub{q}")
            snap = env.node_get(url, "/admin/ec/scrub_status") or {}
        except HttpError as e:
            env.write(f"{url}  unreachable: {e}")
            continue
        env.write(
            f"{url}  passes={int(snap.get('passes', 0))} "
            f"volumes={int(snap.get('volumes_scrubbed', 0))} "
            f"slabs={int(snap.get('slabs', 0))} "
            f"verified={int(snap.get('bytes_verified', 0)) >> 20}MB "
            f"@{snap.get('last_pass_mbps', 0.0):.1f}MB/s "
            f"corrupt(slabs/cols)={int(snap.get('corrupt_slabs', 0))}/"
            f"{int(snap.get('corrupt_columns', 0))} "
            f"findings={int(snap.get('findings', 0))} "
            f"dispatch(host/dev)={int(snap.get('host_dispatches', 0))}/"
            f"{int(snap.get('device_dispatches', 0))} "
            f"skipped(owner/missing)="
            f"{int(snap.get('skipped_not_owner', 0))}/"
            f"{int(snap.get('skipped_missing', 0))} "
            f"errors={int(snap.get('errors', 0))}")
