"""cluster.* / trace.* — fleet health plane commands.

`cluster.health` renders the master's per-holder health fold
(/cluster/health: worst-observer ec_holder_health scores, latency
EWMAs, hedge-loss attribution); `trace.export` fans a trace id out to
every cluster node's /admin/traces/export, merges the per-node Chrome
trace events by span id, normalizes clock skew, and writes one
Perfetto-loadable file.
"""

from __future__ import annotations

import json
from typing import List

from ..server.http_util import HttpError, http_call
from ..util import trace_export
from .command_env import CommandEnv, command, parse_flags


@command("cluster.health",
         "[-refresh false]: per-holder health scores aggregated across "
         "the fleet (latency/error/hedge-loss EWMAs from every node's "
         "reader stack; worst observer wins)")
def cluster_health(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    path = "/cluster/health"
    if flags.get("refresh", "true") != "false":
        path += "?refresh=1"
    view = env.master_get(path)
    holders = view.get("holders") or {}
    nodes = view.get("nodes") or []
    fresh = sum(1 for n in nodes if not n.get("stale"))
    env.write(f"cluster.health: {len(holders)} holders scored by "
              f"{fresh}/{len(nodes)} fresh nodes")
    for n in nodes:
        if n.get("stale"):
            err = n.get("last_error") or "no fresh scrape"
            env.write(f"  node {n['node']}  STALE ({err})")
    for holder in sorted(holders, key=lambda h: holders[h]["score"]):
        h = holders[holder]
        lats = " ".join(f"{kind}={ms:.1f}ms" for kind, ms in
                        sorted(h.get("latency_ewma_ms", {}).items()))
        ev = h.get("events", {})
        env.write(
            f"  {holder}  score={h['score']:.3f}"
            f"{('  ' + lats) if lats else ''}"
            f"  reads={int(ev.get('reads', 0))}"
            f" errors={int(ev.get('errors', 0))}"
            f" hedges_lost={int(ev.get('hedges_lost', 0))}")


@command("cluster.repairs",
         "[-refresh false]: the master's repair queue — open durability "
         "incidents by priority (corruption > lost shard > at-risk "
         "holder) and time-to-re-protection over recent repairs")
def cluster_repairs(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    path = "/cluster/repairs"
    if flags.get("refresh", "true") != "false":
        path += "?refresh=1"
    view = env.master_get(path)
    open_incs = view.get("open") or []
    ttr = view.get("time_to_re_protection") or {}
    counters = view.get("counters") or {}
    env.write(f"cluster.repairs: {len(open_incs)} open, "
              f"{int(counters.get('resolved', 0))} resolved "
              f"(ttr p50={ttr.get('p50_s', 0.0):.1f}s "
              f"p99={ttr.get('p99_s', 0.0):.1f}s "
              f"over {int(ttr.get('count', 0))})")
    for inc in open_incs:
        where = f"volume {inc.get('volume')}.{inc.get('shard')}" \
            if inc.get("volume") is not None else inc.get("holder", "?")
        env.write(f"  [{inc.get('kind')}] {where}"
                  f"  attempts={int(inc.get('attempts', 0))}"
                  f"  since={inc.get('detected_at', 0.0):.0f}"
                  + (f"  err={inc['last_error']}"
                     if inc.get("last_error") else ""))
    for inc in (view.get("resolved_recent") or [])[-5:]:
        env.write(f"  done [{inc.get('kind')}] volume "
                  f"{inc.get('volume')}.{inc.get('shard')} via "
                  f"{inc.get('via')} "
                  f"ttr={inc.get('time_to_re_protection_s', 0.0):.1f}s")


@command("cluster.devices",
         ": device-runtime snapshot per node (GET /admin/devices) — "
         "platform, device kind×count, XLA compiles/recompiles with the "
         "latched sentinel, and cached constant bytes")
def cluster_devices(env: CommandEnv, args: List[str]):
    nodes = env.cluster_nodes()
    env.write(f"cluster.devices: {len(nodes)} nodes")
    for n in nodes:
        url = n["url"]
        try:
            snap = env.node_get(url, "/admin/devices")
        except HttpError as e:
            env.write(f"  {url}  unreachable: {e}")
            continue
        inv = snap.get("inventory") or {}
        stats = snap.get("stats") or {}
        kinds = " ".join(f"{kind}x{count}" for kind, count in
                         sorted((inv.get("device_kinds") or {}).items()))
        compiles = sum((stats.get("compiles") or {}).values())
        recompiles = sum((stats.get("recompiles") or {}).values())
        occ = stats.get("const_cache_occupancy") or {}
        sentinel = "  SENTINEL" if stats.get("sentinel") else ""
        env.write(
            f"  {url}  platform={inv.get('platform')}"
            f"  devices={kinds or 'none'}"
            f"  compiles={compiles} recompiles={recompiles}"
            f"  const_cache={occ.get('entries', 0)}"
            f"/{occ.get('bytes', 0)}B{sentinel}")
        for off in (stats.get("offenders") or []):
            env.write(f"    recompile offender: {off}")


@command("cluster.profile",
         "[-seconds 2] [-o <file>]: sample every server's Python "
         "threads (POST /admin/profile) and merge the collapsed stacks "
         "into one flamegraph/speedscope-ready folded file, each stack "
         "prefixed with its node")
def cluster_profile_cmd(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    try:
        seconds = float(flags.get("seconds", "2"))
    except ValueError:
        env.write("usage: cluster.profile [-seconds N] [-o <file>]")
        return
    out_path = flags.get("o") or "cluster_profile.folded"
    targets = [env.master_url] + \
        [n["url"] for n in env.cluster_nodes()]
    # serial on purpose: the profiler is serialized per PROCESS (409 on
    # overlap), and a test cluster runs every server in one process —
    # a parallel fan-out there would profile one node and bounce off
    # the rest
    merged: List[str] = []
    sampled = 0
    for url in targets:
        try:
            folded = http_call(
                "POST",
                f"http://{url}/admin/profile?seconds={seconds:g}",
                timeout=seconds + 30.0).decode("utf-8", "replace")
        except Exception as e:  # noqa: BLE001 - a down node must not
            # abort the sweep
            env.write(f"  {url}  unreachable: {e}")
            continue
        lines = [ln for ln in folded.splitlines() if ln.strip()]
        if lines:
            sampled += 1
        merged.extend(f"{url};{ln}" for ln in lines)
    if not merged:
        env.write("cluster.profile: no samples collected")
        return
    with open(out_path, "w") as f:
        f.write("\n".join(merged) + "\n")
    env.write(f"cluster.profile: {len(merged)} stacks from "
              f"{sampled}/{len(targets)} nodes over {seconds:g}s "
              f"-> {out_path}")


@command("trace.export",
         "-trace <id> [-o <file>]: merge one trace's spans from every "
         "cluster node into a single skew-normalized Chrome trace-event "
         "file (open in Perfetto / chrome://tracing)")
def trace_export_cmd(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    tid = flags.get("trace")
    if not tid:
        env.write("usage: trace.export -trace <id> [-o <file>]")
        return
    out_path = flags.get("o") or f"trace_{tid[:12]}.json"
    targets = [env.master_url] + \
        [n["url"] for n in env.cluster_nodes()]
    span_lists = []
    reached = 0
    for url in targets:
        try:
            obj = env.node_get(url,
                               f"/admin/traces/export?trace={tid}")
        except HttpError as e:
            env.write(f"  {url}  unreachable: {e}")
            continue
        reached += 1
        span_lists.append(trace_export.spans_from_chrome(obj))
    if not any(span_lists):
        env.write(f"trace.export: no spans for trace {tid} on "
                  f"{reached} reachable nodes")
        return
    merged = trace_export.merged_chrome_trace(span_lists)
    with open(out_path, "w") as f:
        json.dump(merged, f)
    meta = merged.get("metadata", {})
    env.write(
        f"trace.export: {meta.get('span_count', 0)} spans from "
        f"{len(meta.get('nodes', []))} nodes -> {out_path} "
        f"(clock offsets: "
        f"{json.dumps(meta.get('clock_offsets_s', {}))})")
