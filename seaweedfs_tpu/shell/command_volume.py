"""volume.* shell commands (reference weed/shell/command_volume_*.go)."""

from __future__ import annotations

from typing import List

from ..storage.types import ReplicaPlacement
from .command_env import CommandEnv, command, parse_flags


@command("volume.list", ": list volumes per server")
def volume_list(env: CommandEnv, args: List[str]):
    for node in env.cluster_nodes():
        env.write(f"{node['url']}  volumes={node['volumes']} "
                  f"ec_shards={node['ec_shards']} free={node['free']:.1f}")
    for vid_s, replicas in sorted(env.all_volumes().items(),
                                  key=lambda kv: int(kv[0])):
        vi = replicas[0]
        env.write(f"  volume {vid_s}: collection={vi.get('collection', '')!r}"
                  f" size={vi.get('size', 0)} files={vi.get('file_count', 0)}"
                  f" deleted={vi.get('delete_count', 0)}"
                  f" rp={vi.get('replica_placement', '000')}"
                  f" replicas={[r['url'] for r in replicas]}"
                  f"{' readonly' if vi.get('read_only') else ''}")
    for vid_s, info in sorted(env.ec_volumes().items(),
                              key=lambda kv: int(kv[0])):
        env.write(f"  ec volume {vid_s}: "
                  f"collection={info.get('collection', '')!r} shards="
                  + ", ".join(f"{s}@{','.join(u)}"
                              for s, u in sorted(info["shards"].items(),
                                                 key=lambda kv: int(kv[0]))))


@command("volume.copy",
         "-volumeId <id> -target <url> [-source <url>] : copy a volume "
         "to another server (source kept)")
def volume_copy(env: CommandEnv, args: List[str]):
    """Reference command_volume_copy.go: target pulls the volume's
    files from the source; unlike volume.move the source stays. Shares
    volume.move's audited freeze/copy/thaw sequence."""
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    target = flags["target"]
    replicas = env.all_volumes().get(str(vid), [])
    if not replicas:
        env.write(f"volume {vid} not found")
        return
    source = flags.get("source", replicas[0]["url"])
    collection = replicas[0].get("collection", "")
    _frozen_copy(env, vid, collection, source, target, replicas,
                 delete_source=False)
    env.write(f"volume {vid}: copied {source} -> {target}")


@command("volume.configure.replication",
         "-volumeId <id> -replication <xyz> : change a volume's "
         "replica placement")
def volume_configure_replication(env: CommandEnv, args: List[str]):
    """Reference command_volume_configure_replication.go: rewrite the
    superblock placement byte on every holder; the master adopts the
    new placement from the next heartbeats (repair to the new level is
    then volume.fix.replication's job)."""
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    replication = flags["replication"]
    replicas = env.all_volumes().get(str(vid), [])
    if not replicas:
        env.write(f"volume {vid} not found")
        return
    done, failed = [], []
    for r in replicas:
        try:
            env.node_post(r["url"],
                          f"/admin/volume/configure_replication"
                          f"?volume={vid}&replication={replication}")
            done.append(r["url"])
        except Exception as e:  # noqa: BLE001 - per-holder report
            failed.append((r["url"], str(e)))
    env.write(f"volume {vid}: replication -> {replication} on "
              f"{len(done)} holder(s)")
    for url, err in failed:
        env.write(f"  FAILED on {url}: {err}")
    if done and failed:
        env.write(f"  WARNING: holders now disagree on placement — "
                  f"fix the failures and re-run")


@command("volume.move",
         "-volumeId <id> -target <url> : move a volume to another server")
def volume_move(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    target = flags["target"]
    replicas = env.all_volumes().get(str(vid), [])
    if not replicas:
        env.write(f"volume {vid} not found")
        return
    source = flags.get("source", replicas[0]["url"])
    collection = replicas[0].get("collection", "")
    _move_volume(env, vid, collection, source, target, replicas)
    env.write(f"volume {vid}: {source} -> {target}")


def _frozen_copy(env: CommandEnv, vid: int, collection: str, source: str,
                 target: str, replicas, delete_source: bool):
    """Freeze -> copy [-> delete source] -> thaw exactly what WE froze.
    Without the freeze, writes landing after the .idx snapshot would be
    lost (the copy is .idx-then-.dat). Replicas that were already
    readonly (an operator's deliberate freeze, a keep-local tiered
    volume) are left untouched — and left frozen afterwards."""
    froze = []
    deleted = False
    try:
        for r in replicas:
            # freeze unconditionally (idempotent); the response's
            # was_readonly — the holder's OWN prior state, not the
            # master's heartbeat-delayed view — decides what to thaw
            out = env.node_post(r["url"],
                                f"/admin/volume/readonly?volume={vid}")
            if not (out or {}).get("was_readonly"):
                froze.append(r["url"])
        env.node_post(target, f"/admin/volume/copy?volume={vid}"
                              f"&collection={collection}&source={source}")
        if delete_source:
            env.node_post(source, f"/admin/delete_volume?volume={vid}")
            deleted = True
    finally:
        # thaw our freezes even when the copy or delete blew up mid-way
        for url in froze:
            if deleted and url == source:
                continue
            try:
                env.node_post(url, f"/admin/volume/readonly?volume={vid}"
                                   f"&readonly=false")
            except Exception:
                pass


def _move_volume(env: CommandEnv, vid: int, collection: str, source: str,
                 target: str, replicas):
    _frozen_copy(env, vid, collection, source, target, replicas,
                 delete_source=True)


@command("volume.balance", ": even out volume counts across servers")
def volume_balance(env: CommandEnv, args: List[str]):
    moves = 0
    while True:
        nodes = env.cluster_nodes()
        if len(nodes) < 2:
            break
        counts = {n["url"]: n["volumes"] for n in nodes}
        hi = max(counts, key=counts.get)
        lo = min(counts, key=counts.get)
        if counts[hi] - counts[lo] <= 1:
            break
        # pick a volume on hi that lo doesn't hold
        movable = None
        for vid_s, replicas in env.all_volumes().items():
            urls = [r["url"] for r in replicas]
            if hi in urls and lo not in urls:
                movable = (int(vid_s), replicas[0].get("collection", ""),
                           replicas)
                break
        if movable is None:
            break
        vid, collection, replicas = movable
        _move_volume(env, vid, collection, hi, lo, replicas)
        env.write(f"moved volume {vid}: {hi} -> {lo}")
        moves += 1
        if moves > 100:
            break
    env.write(f"volume.balance: {moves} moves")


@command("volume.fix.replication",
         ": re-replicate under-replicated volumes")
def volume_fix_replication(env: CommandEnv, args: List[str]):
    fixed = 0
    nodes = env.cluster_nodes()
    for vid_s, replicas in env.all_volumes().items():
        vi = replicas[0]
        rp = ReplicaPlacement.parse(vi.get("replica_placement", "000"))
        have = [r["url"] for r in replicas]
        if len(have) >= rp.copy_count:
            continue
        candidates = [n["url"] for n in
                      sorted(nodes, key=lambda n: -n.get("free", 0))
                      if n["url"] not in have and n.get("free", 0) >= 1]
        needed = rp.copy_count - len(have)
        for target in candidates[:needed]:
            env.node_post(target,
                          f"/admin/volume/copy?volume={vid_s}"
                          f"&collection={vi.get('collection', '')}"
                          f"&source={have[0]}")
            env.write(f"volume {vid_s}: replicated to {target}")
            fixed += 1
    env.write(f"volume.fix.replication: {fixed} copies made")


@command("volume.fsck", "[-deep] : check volume integrity cluster-wide")
def volume_fsck(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    total = bad = 0
    for vid_s, replicas in sorted(env.all_volumes().items(),
                                  key=lambda kv: int(kv[0])):
        for r in replicas:
            total += 1
            if flags.get("deep"):
                out = env.node_post(r["url"],
                                    f"/admin/volume/verify?volume={vid_s}")
                status = f"checked={out['checked']} errors={out['errors']}"
                if out["errors"]:
                    bad += 1
            else:
                status = f"files={r.get('file_count', 0)}"
            env.write(f"volume {vid_s} @ {r['url']}: {status}")
    env.write(f"volume.fsck: {total} replicas, {bad} with errors")


@command("volume.vacuum", "[-garbageThreshold 0.3] : trigger vacuum")
def volume_vacuum(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    q = f"?garbageThreshold={flags.get('garbageThreshold', 0.3)}"
    out = env.master_post(f"/vol/vacuum{q}")
    for r in out.get("vacuumed", []):
        env.write(f"volume {r['volume']}: "
                  f"{'vacuumed' if r['ok'] else 'FAILED'}")


@command("volume.delete", "-volumeId <id> : delete a volume everywhere")
def volume_delete(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    for r in env.all_volumes().get(str(vid), []):
        env.node_post(r["url"], f"/admin/delete_volume?volume={vid}")
        env.write(f"volume {vid}: deleted on {r['url']}")


@command("volume.tier.upload",
         "-volumeId <id> -dest <kind.id> [-keepLocalDatFile] : move a "
         "volume's .dat to a remote tier backend")
def volume_tier_upload(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    dest = flags["dest"]
    replicas = env.all_volumes().get(str(vid), [])
    if not replicas:
        env.write(f"volume {vid} not found")
        return
    # freeze every replica, then ship from ONE location (reference
    # doVolumeTierUpload): replica .dat files are not byte-identical in
    # general, so two uploaders racing on one backend key would corrupt
    # the tier for whichever .idx loses
    frozen = []
    keep = "true" if flags.get("keepLocalDatFile") else "false"
    try:
        for r in replicas:
            # freeze unconditionally; the holder's OWN was_readonly
            # (not the master's heartbeat-delayed view) decides what a
            # failure path may thaw — same discipline as _frozen_copy
            out = env.node_post(r["url"],
                                f"/admin/volume/readonly?volume={vid}")
            if not (out or {}).get("was_readonly"):
                frozen.append(r["url"])
        r = replicas[0]
        info = env.node_post(
            r["url"], f"/admin/volume/tier_upload?volume={vid}"
                      f"&dest={dest}&keep_local={keep}")
    except Exception:
        # thaw exactly the replicas this command froze — a failure at
        # any point (a later freeze included) must not leave the
        # volume permanently unwritable; one unreachable node must not
        # stop the others from thawing or mask the original error
        for url in frozen:
            try:
                env.node_post(
                    url, f"/admin/volume/readonly?volume={vid}"
                         f"&readonly=false")
            except Exception:
                pass
        raise
    env.write(f"volume {vid} @ {r['url']}: .dat -> "
              f"{info['remote']['backend']}/{info['remote']['key']} "
              f"({info['remote']['file_size']} bytes)")


@command("volume.tier.download",
         "-volumeId <id> [-deleteRemote] : bring a tiered volume's .dat "
         "back to local disk")
def volume_tier_download(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    delete = "true" if flags.get("deleteRemote") else "false"
    replicas = env.all_volumes().get(str(vid), [])
    if not replicas:
        env.write(f"volume {vid} not found")
        return
    from ..server.http_util import HttpError
    brought = 0
    for r in replicas:
        try:
            out = env.node_post(
                r["url"], f"/admin/volume/tier_download?volume={vid}"
                          f"&delete_remote={delete}")
        except HttpError as e:
            if "no remote tier" in str(e):
                continue       # this replica kept its local .dat
            raise
        brought += 1
        env.write(f"volume {vid} @ {r['url']}: .dat local again "
                  f"({out['size']} bytes)")
    if not brought:
        env.write(f"volume {vid}: no replica is tiered")


@command("volume.mount",
         "-volumeId <id> -node <url> : serve an on-disk volume")
def volume_mount(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    out = env.node_post(
        flags["node"],
        f"/admin/volume/mount?volume={flags['volumeId']}")
    env.write(f"volume {flags['volumeId']}: mounted={out.get('mounted')}")


@command("volume.unmount",
         "-volumeId <id> -node <url> : stop serving (files stay on disk)")
def volume_unmount(env: CommandEnv, args: List[str]):
    flags = parse_flags(args)
    out = env.node_post(
        flags["node"],
        f"/admin/volume/unmount?volume={flags['volumeId']}")
    env.write(f"volume {flags['volumeId']}: "
              f"unmounted={out.get('unmounted')}")
