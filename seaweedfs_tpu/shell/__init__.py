"""shell — the admin REPL and maintenance commands.

Reference weed/shell: 30+ self-registered commands driving the cluster
through the master + volume-server APIs. Commands register themselves into
COMMANDS via the @command decorator.
"""

from .command_env import CommandEnv, COMMANDS, command  # noqa: F401
from . import command_volume  # noqa: F401  (registers volume.* commands)
from . import command_ec  # noqa: F401  (registers ec.* commands)
from . import command_fs  # noqa: F401  (registers fs.* commands)
from . import command_bucket  # noqa: F401  (registers bucket.* commands)
from . import command_collection  # noqa: F401
from . import command_cluster  # noqa: F401  (cluster.health, trace.export)
