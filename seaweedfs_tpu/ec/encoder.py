"""Volume -> EC shard files (.dat -> .ec00..ec13), sorted index, rebuild.

Behavior-compatible with reference ec_encoder.go:
  * write_sorted_file_from_idx: .idx append log -> .ecx (same 16B entries,
    sorted by needle id) [ec_encoder.go:27-54]
  * write_ec_files: two-level striping — while MORE than one large row
    (10 x 1GB) remains, emit a large row; tail as small rows (10 x 1MB),
    zero-padded [ec_encoder.go:192-229]
  * rebuild_ec_files: regenerate missing .ecNN from >=10 survivors
    [ec_encoder.go:61-116, 231-285]

TPU-first difference: the reference streams 10 x 256KB buffers per GF call;
here each device call covers a whole slab (default 10 x 8MB) so a volume
encode is a few hundred kernel launches instead of ~120k, and the GF math
runs as one MXU matmul per slab (ops/rs_tpu.py). Slab reads are strided
(block i of a row lives at start + i*block_size), the same column layout
the reference uses, so shard bytes are identical.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..ops.codec import ReedSolomonCodec, get_codec
from ..storage.needle_map import MemDb
from .constants import (DATA_SHARDS, LARGE_BLOCK_SIZE, PARITY_SHARDS,
                        SMALL_BLOCK_SIZE, TOTAL_SHARDS, to_ext)

DEFAULT_SLAB = 8 << 20  # bytes per shard per device call


def write_sorted_file_from_idx(base_name: str, ext: str = ".ecx"):
    """Build the sorted EC index next to the volume files."""
    db = MemDb.load_from_idx(base_name + ".idx")
    db.save_to_idx(base_name + ext)


def write_ec_files(base_name: str, codec: Optional[ReedSolomonCodec] = None,
                   large_block: int = LARGE_BLOCK_SIZE,
                   small_block: int = SMALL_BLOCK_SIZE,
                   slab: int = DEFAULT_SLAB):
    """Encode base_name.dat into base_name.ec00 .. .ec13."""
    codec = codec or get_codec(DATA_SHARDS, PARITY_SHARDS)
    dat_path = base_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    outs = [open(base_name + to_ext(i), "wb") for i in range(TOTAL_SHARDS)]
    try:
        with open(dat_path, "rb") as f:
            remaining = dat_size
            processed = 0
            large_row = large_block * DATA_SHARDS
            while remaining > large_row:
                _encode_row(f, codec, processed, large_block, slab, outs)
                remaining -= large_row
                processed += large_row
            small_row = small_block * DATA_SHARDS
            while remaining > 0:
                _encode_row(f, codec, processed, small_block, slab, outs)
                remaining -= small_row
                processed += small_row
    finally:
        for o in outs:
            o.close()


def _encode_row(f, codec: ReedSolomonCodec, start: int, block_size: int,
                slab: int, outs: List):
    """Encode one row of 10 blocks at [start, start + 10*block_size)."""
    step = min(slab, block_size)
    for off in range(0, block_size, step):
        width = min(step, block_size - off)  # final chunk may be partial
        data = np.zeros((DATA_SHARDS, width), dtype=np.uint8)
        for i in range(DATA_SHARDS):
            f.seek(start + i * block_size + off)
            chunk = f.read(width)
            if chunk:
                data[i, :len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
        parity = codec.encode(data)
        for i in range(DATA_SHARDS):
            outs[i].write(data[i].tobytes())
        for j in range(PARITY_SHARDS):
            outs[DATA_SHARDS + j].write(parity[j].tobytes())


def rebuild_ec_files(base_name: str,
                     codec: Optional[ReedSolomonCodec] = None,
                     slab: int = DEFAULT_SLAB) -> List[int]:
    """Regenerate missing shard files from survivors. Returns the list of
    rebuilt shard ids. Raises if fewer than DATA_SHARDS survive."""
    codec = codec or get_codec(DATA_SHARDS, PARITY_SHARDS)
    present = [os.path.exists(base_name + to_ext(i))
               for i in range(TOTAL_SHARDS)]
    missing = [i for i, p in enumerate(present) if not p]
    if not missing:
        return []
    if sum(present) < DATA_SHARDS:
        raise ValueError(
            f"cannot rebuild: only {sum(present)} of {TOTAL_SHARDS} shards")
    shard_size = None
    for i, p in enumerate(present):
        if p:
            sz = os.path.getsize(base_name + to_ext(i))
            if shard_size is None:
                shard_size = sz
            elif shard_size != sz:
                raise ValueError("surviving shards differ in size")
    ins = [open(base_name + to_ext(i), "rb") if present[i] else None
           for i in range(TOTAL_SHARDS)]
    outs = {i: open(base_name + to_ext(i), "wb") for i in missing}
    try:
        for off in range(0, shard_size, slab):
            n = min(slab, shard_size - off)
            shards: List[Optional[np.ndarray]] = []
            for i in range(TOTAL_SHARDS):
                if ins[i] is None:
                    shards.append(None)
                else:
                    ins[i].seek(off)
                    shards.append(np.frombuffer(ins[i].read(n),
                                                dtype=np.uint8))
            rebuilt = codec.reconstruct(shards)
            for i in missing:
                outs[i].write(rebuilt[i].tobytes())
    finally:
        for h in ins:
            if h is not None:
                h.close()
        for h in outs.values():
            h.close()
    return missing


def ec_shard_base_size(dat_size: int, large_block: int = LARGE_BLOCK_SIZE,
                       small_block: int = SMALL_BLOCK_SIZE) -> int:
    """Size every shard file will have for a given .dat size."""
    large_row = large_block * DATA_SHARDS
    n_large = 0
    remaining = dat_size
    while remaining > large_row:
        n_large += 1
        remaining -= large_row
    small_row = small_block * DATA_SHARDS
    n_small = (remaining + small_row - 1) // small_row
    return n_large * large_block + n_small * small_block
