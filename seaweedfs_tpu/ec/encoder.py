"""Volume -> EC shard files (.dat -> .ec00..ec13), sorted index, rebuild.

Behavior-compatible with reference ec_encoder.go:
  * write_sorted_file_from_idx: .idx append log -> .ecx (same 16B entries,
    sorted by needle id) [ec_encoder.go:27-54]
  * write_ec_files: two-level striping — while MORE than one large row
    (k x 1GB) remains, emit a large row; tail as small rows (k x 1MB),
    zero-padded [ec_encoder.go:192-229]
  * rebuild_ec_files: regenerate missing .ecNN from >=k survivors
    [ec_encoder.go:61-116, 231-285]

Geometry is taken from the codec (generic RS(k,m), default 10+4 — the
reference hardcodes 10+4 at ec_encoder.go:17-20).

TPU-first difference: the reference streams k x 256KB buffers per GF call;
here each device call covers a whole slab (default k x 8MB) so a volume
encode is a few hundred kernel launches instead of ~120k, and the GF math
runs as one MXU matmul per slab (ops/rs_tpu.py). With a TPU-backed codec
the slabs additionally flow through ops/pipeline.PipelinedMatmul, which
overlaps disk reads (reader thread), h2d, MXU compute, d2h and shard-file
writes. Slab reads are strided (block i of a row lives at start +
i*block_size), the same column layout the reference uses, so shard bytes
are identical across all backends.
"""

from __future__ import annotations

import os
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..ops.codec import ReedSolomonCodec, get_codec
from ..storage.needle_map import MemDb
from ..util import tracing
from ..util.profiling import StageTimer
from .constants import (DATA_SHARDS, LARGE_BLOCK_SIZE, PARITY_SHARDS,
                        SMALL_BLOCK_SIZE, to_ext)

DEFAULT_SLAB = 8 << 20  # bytes per shard per device call


def write_sorted_file_from_idx(base_name: str, ext: str = ".ecx"):
    """Build the sorted EC index next to the volume files. Record width
    follows the volume's offset width (superblock flag; 5-byte-offset
    volumes have 17B .idx/.ecx records)."""
    width = 4
    try:
        from ..storage.super_block import SUPER_BLOCK_SIZE, SuperBlock
        with open(base_name + ".dat", "rb") as f:
            width = SuperBlock.from_bytes(
                f.read(SUPER_BLOCK_SIZE)).offset_width
    except Exception:  # noqa: BLE001 - no/short .dat: default width
        pass
    db = MemDb.load_from_idx(base_name + ".idx", width)
    db.save_to_idx(base_name + ext)


def _row_slabs(f, k: int, start: int, block_size: int, slab: int,
               timer: Optional[StageTimer] = None
               ) -> Iterator[Tuple[None, np.ndarray]]:
    """Yield the slabs of one row of k blocks at [start, start+k*block)."""
    step = min(slab, block_size)
    for off in range(0, block_size, step):
        width = min(step, block_size - off)  # final chunk may be partial
        t0 = time.perf_counter()
        data = np.zeros((k, width), dtype=np.uint8)
        for i in range(k):
            f.seek(start + i * block_size + off)
            chunk = f.read(width)
            if chunk:
                data[i, :len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
        if timer is not None:
            end = time.perf_counter()
            timer.add("disk_read", end - t0, k * width, interval=(t0, end))
        yield None, data


def _dat_slabs(dat_path: str, dat_size: int, k: int, large_block: int,
               small_block: int, slab: int,
               timer: Optional[StageTimer] = None
               ) -> Iterator[Tuple[None, np.ndarray]]:
    """All slabs of a .dat in shard-file order (large rows, then small)."""
    with open(dat_path, "rb") as f:
        remaining = dat_size
        processed = 0
        large_row = large_block * k
        while remaining > large_row:
            yield from _row_slabs(f, k, processed, large_block, slab, timer)
            remaining -= large_row
            processed += large_row
        small_row = small_block * k
        while remaining > 0:
            yield from _row_slabs(f, k, processed, small_block, slab, timer)
            remaining -= small_row
            processed += small_row


def _window_batches(slabs: Iterator[Tuple[None, np.ndarray]],
                    window: int) -> Iterator[Tuple[None, np.ndarray]]:
    """Re-chunk a slab stream onto sub-chunk window boundaries.

    The piggyback parity transform is window-local (ops/codec.pb_split
    interleaves alpha sub-chunks per window), so every batch fed to the
    encode matmul must be a whole number of windows. Slab widths from
    the block reader are arbitrary, but shards append contiguously, so
    buffering the non-aligned remainder into the next batch preserves
    shard bytes exactly. The stream total is window-aligned by
    construction (both stripe blocks divide by the window), so the
    buffer always drains."""
    held: Optional[np.ndarray] = None
    for _, data in slabs:
        if held is not None:
            data = np.concatenate([held, data], axis=1)
            held = None
        cut = (data.shape[1] // window) * window
        if cut < data.shape[1]:
            held = np.ascontiguousarray(data[:, cut:])
            data = data[:, :cut]
        if data.shape[1]:
            yield None, np.ascontiguousarray(data)
    if held is not None and held.shape[1]:
        raise ValueError(
            f"stream tail of {held.shape[1]} bytes is not window-aligned "
            f"(window {window}); block sizes must divide by the window")


def piggyback_geometry(codec: ReedSolomonCodec, layout,
                       large_block: int, small_block: int):
    """Resolve (plan, window) for a piggyback encode/rebuild and check
    the stripe geometry supports sub-chunking: the window must divide
    both stripe blocks so every shard size is window-aligned."""
    from ..ops import codec as ops_codec
    pplan = ops_codec.piggyback_plan(
        codec.k, codec.m, matrix_kind=getattr(codec, "matrix_kind",
                                              "vandermonde"),
        matrix=getattr(codec, "matrix", None))
    window = ops_codec.pb_window(small_block, pplan.alpha)
    if large_block % window:
        raise ValueError(
            f"piggyback layout: large block {large_block} not divisible "
            f"by the sub-chunk window {window}")
    return pplan, window


def _coalesce_slabs(slabs: Iterator[Tuple[None, np.ndarray]],
                    target_width: int) -> Iterator[Tuple[None, np.ndarray]]:
    """Hstack consecutive row-slabs up to target_width per device call.

    GF coding is columnwise-independent, so concat-then-encode equals
    encode-then-concat; and consecutive slabs append contiguously to each
    shard file, so the batched rows are exactly the shard byte ranges —
    the 'streaming stripe batches' of BASELINE config 3. Without this, a
    volume of 1MB small rows would reach the device 10MB per call.
    """
    batch: List[np.ndarray] = []
    total = 0
    for _, data in slabs:
        w = data.shape[1]
        if batch and total + w > target_width:
            yield None, (batch[0] if len(batch) == 1
                         else np.concatenate(batch, axis=1))
            batch, total = [], 0
        batch.append(data)
        total += w
    if batch:
        yield None, (batch[0] if len(batch) == 1
                     else np.concatenate(batch, axis=1))


def write_ec_files(base_name: str, codec: Optional[ReedSolomonCodec] = None,
                   large_block: int = LARGE_BLOCK_SIZE,
                   small_block: int = SMALL_BLOCK_SIZE,
                   slab: int = DEFAULT_SLAB,
                   pipelined: Optional[bool] = None,
                   timer: Optional[StageTimer] = None,
                   sink=None,
                   layout: str = "flat"):
    """Encode base_name.dat into base_name.ec00 .. .ec{k+m-1}.

    pipelined: None = auto (pipeline when the codec is device-backed);
    True/False forces. The synchronous path and the pipelined path produce
    byte-identical shard files. ``timer`` collects a per-stage breakdown
    (disk_read / h2d / d2h+mxu / shard_write / waits) for bench/profiling.

    ``sink``: when given (an ec.spread.StripedSpreadSink), the stripe
    stream is teed into ``sink.write_stripe(data, parity)`` instead of
    local shard files — each stripe is the next slab-aligned byte range
    of every shard, pushed to its holder while later slabs encode. The
    caller owns the sink lifecycle (finish/abort).

    ``layout``: "flat" (default; plain RS parity) or "piggyback"
    (coupled sub-chunk parity, ops/codec.piggyback_plan). Data shard
    bytes are identical under both layouts — only the parity rows
    differ, computed per window by one (m*alpha, k*alpha) matmul on
    the same kernels. Callers record the layout in the volume's
    sidecars (ec/layout.py); this function only shapes the bytes.
    """
    from ..ops import codec as ops_codec
    codec = codec or get_codec(DATA_SHARDS, PARITY_SHARDS)
    k, m = codec.k, codec.m
    if pipelined is None:
        pipelined = codec.backend in ("tpu", "mesh")
    piggyback = layout == "piggyback"
    pplan = window = None
    if piggyback:
        pplan, window = piggyback_geometry(codec, layout, large_block,
                                           small_block)
    dat_path = base_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    # always collect stages: the per-phase spans below need them even
    # when no caller asked for a bench breakdown
    timer = timer if timer is not None else StageTimer()
    slabs = _dat_slabs(dat_path, dat_size, k, large_block, small_block, slab,
                       timer)
    outs = [] if sink is not None else \
        [open(base_name + to_ext(i), "wb") for i in range(k + m)]
    # device-parallel compute feeding holder-parallel network: with a
    # piecewise-draining codec (mesh) and a sink, each device shard's
    # parity piece is routed to the per-target send queues the moment
    # its d2h lands — the host never stages the full (m, slab) output.
    # The piggyback transform is window-interleaved, so its parity must
    # merge whole slabs: no pieces.
    pieces = pipelined and sink is not None and \
        hasattr(codec, "drain_pieces") and not piggyback
    try:
        if piggyback:
            batches = _window_batches(
                _coalesce_slabs(slabs, max(slab - slab % window, window)),
                window)
            alpha = pplan.alpha

            def pb_stream():
                if pipelined:
                    from ..ops.pipeline import PipelinedMatmul
                    pm = PipelinedMatmul(
                        pplan.emat,
                        max_width=max(slab // alpha, window // alpha),
                        timer=timer, codec=codec)
                    split = ((data, ops_codec.pb_split(data, alpha, window))
                             for _, data in batches)
                    for orig, _sub, psub in pm.stream(split):
                        yield orig, ops_codec.pb_merge(
                            np.asarray(psub, dtype=np.uint8), alpha, window)
                else:
                    for _, data in batches:
                        sub = ops_codec.pb_split(data, alpha, window)
                        psub = np.asarray(
                            codec._matmul(pplan.emat, sub), dtype=np.uint8)
                        yield data, ops_codec.pb_merge(psub, alpha, window)

            stream = ((None, data, parity) for data, parity in pb_stream())
        elif pipelined:
            from ..ops.pipeline import PipelinedMatmul
            pm = PipelinedMatmul(codec.matrix[k:], max_width=slab,
                                 timer=timer, codec=codec, pieces=pieces)
            stream = pm.stream(_coalesce_slabs(slabs, slab))
        else:
            stream = ((meta, data, codec.encode(data))
                      for meta, data in slabs)
        for _, data, parity in stream:
            t0 = time.perf_counter()
            if pieces:
                nbytes = 0
                for lo, piece in parity:
                    pw = piece.shape[1]
                    sink.write_stripe(data[:, lo:lo + pw], piece)
                    nbytes += k * pw + piece.nbytes
            elif sink is not None:
                sink.write_stripe(data, parity)
                nbytes = data.nbytes + parity.nbytes
            else:
                for i in range(k):
                    outs[i].write(data[i].tobytes())
                for j in range(m):
                    outs[k + j].write(parity[j].tobytes())
                nbytes = data.nbytes + parity.nbytes
            end = time.perf_counter()
            timer.add("shard_write", end - t0, nbytes, interval=(t0, end))
    finally:
        for o in outs:
            o.close()
    _record_phase_spans(timer, pipelined, op="ec.encode")


def write_ec_files_spread(base_name: str, sink,
                          codec: Optional[ReedSolomonCodec] = None,
                          large_block: int = LARGE_BLOCK_SIZE,
                          small_block: int = SMALL_BLOCK_SIZE,
                          slab: int = DEFAULT_SLAB,
                          pipelined: Optional[bool] = None,
                          stats: Optional[dict] = None,
                          layout: str = "flat"):
    """Streaming encode+spread: tee write_ec_files' stripe stream into
    ``sink`` (an ec.spread.StripedSpreadSink) so each shard's slab
    ranges reach its holder while later slabs are still encoding —
    the write-path mirror of rebuild_ec_files_streaming. Wall
    approaches max(encode, spread); shards bound for remote holders
    never touch the source disk.

    On ANY failure the sink is aborted (``.part`` cleanup on every
    holder) before the exception propagates — callers either get a
    complete finalized shard set or nothing.

    ``stats``, when given, is filled with the spread counters plus
    ``encode_busy_s`` / ``spread_busy_s`` / ``overlap_frac`` — the
    encode-side analogue of the streaming rebuild's gather stats."""
    codec = codec or get_codec(DATA_SHARDS, PARITY_SHARDS)
    if pipelined is None:
        pipelined = codec.backend in ("tpu", "mesh")
    from ..ops import telemetry
    before = telemetry.STATS.snapshot()
    timer = StageTimer()
    t_stream = time.perf_counter()
    try:
        write_ec_files(base_name, codec=codec, large_block=large_block,
                       small_block=small_block, slab=slab,
                       pipelined=pipelined, timer=timer, sink=sink,
                       layout=layout)
        sink.finish()
    except BaseException:
        sink.abort()
        raise
    stream_s = time.perf_counter() - t_stream
    if stats is not None:
        ss = sink.stats
        stats.update(telemetry.delta(before))
        stats.update(ss.snapshot())
        stats["shard_size"] = sink.offset
        stats["stream_s"] = round(stream_s, 3)
        stats["backend"] = codec.backend
        stats["phases"] = {n: round(s, 6) for n, s in
                           _phases_from_timer(timer, pipelined).items()}
        # encode busy = stream wall minus the time the consumer spent
        # blocked on full send windows; spread busy = the union of send
        # intervals across all target workers. The overlap fraction is
        # the same clamped serialized-vs-wall estimate the streaming
        # rebuild reports for gather/compute.
        spread_busy = ss.busy_s()
        encode_busy = max(stream_s - sink.blocked_s, 0.0)
        serialized = encode_busy + spread_busy
        overlap = 0.0
        if serialized > 0:
            overlap = max(0.0, min(1.0,
                                   (serialized - stream_s) / serialized))
        stats["encode_busy_s"] = round(encode_busy, 3)
        stats["spread_busy_s"] = round(spread_busy, 3)
        stats["overlap_frac"] = round(overlap, 4)
        stats["spread_mbps"] = round(ss.mbps(), 1)
        stats["spread_remote_shards"] = ss.remote_shards


def _phases_from_timer(timer: StageTimer, pipelined: bool) -> dict:
    """Map StageTimer stages onto the canonical EC phase names, from
    the consumer thread's perspective: in the pipelined path the waits
    (read_wait / h2d / drain_wait) plus the write stage tile the stream
    wall, so the phases sum to ~the operation time instead of
    double-counting overlapped worker-thread work."""
    t = timer.totals
    return {
        "gather": t.get("read_wait" if pipelined else "disk_read", 0.0),
        "dispatch": t.get("h2d", 0.0),
        "drain": t.get("drain_wait", 0.0),
        "write": t.get("shard_write", 0.0),
    }


def _record_phase_spans(timer: StageTimer, pipelined: bool, op: str):
    for name, secs in _phases_from_timer(timer, pipelined).items():
        if secs > 0:
            tracing.record_span(name, secs, op=op)


def rebuild_ec_files(base_name: str,
                     codec: Optional[ReedSolomonCodec] = None,
                     slab: int = DEFAULT_SLAB,
                     pipelined: Optional[bool] = None,
                     stats: Optional[dict] = None,
                     layout=None) -> List[int]:
    """Regenerate missing shard files from survivors. Returns the list of
    rebuilt shard ids. Raises if fewer than k survive.

    Device-backed codecs (tpu AND mesh) stream survivor slabs through
    PipelinedMatmul with the fused decode plan: one device dispatch per
    slab regenerates every missing shard (data + parity rows stacked),
    with bounded in-flight depth instead of a synchronous per-slab
    round-trip. ``stats``, when given, is filled with the dispatch
    telemetry of this rebuild (dispatches / bitmat_uploads /
    device_bytes / host_fallbacks deltas, survivor_bytes, stream_s) —
    the bench's regression counters.

    ``layout``: an ec.layout.LayoutInfo (or None for flat). Piggyback
    volumes decode through ops/codec.piggyback_decode_plan — the same
    one-fused-dispatch-per-slab stream, with each survivor slab split
    into sub-chunk rows per window before the matmul and each rebuilt
    slab merged back before the write."""
    from ..ops import codec as ops_codec
    codec = codec or get_codec(DATA_SHARDS, PARITY_SHARDS)
    k, total = codec.k, codec.total
    if pipelined is None:
        pipelined = codec.backend in ("tpu", "mesh")
    piggyback = layout is not None and getattr(layout, "piggyback", False)
    present = [os.path.exists(base_name + to_ext(i)) for i in range(total)]
    missing = [i for i, p in enumerate(present) if not p]
    if not missing:
        return []
    if sum(present) < k:
        raise ValueError(
            f"cannot rebuild: only {sum(present)} of {total} shards")
    shard_size = None
    for i, p in enumerate(present):
        if p:
            sz = os.path.getsize(base_name + to_ext(i))
            if shard_size is None:
                shard_size = sz
            elif shard_size != sz:
                raise ValueError("surviving shards differ in size")
    if piggyback:
        return _rebuild_ec_files_piggyback(
            base_name, codec, layout, present, missing, shard_size,
            slab, stats)
    ins = [open(base_name + to_ext(i), "rb") if present[i] else None
           for i in range(total)]
    outs = {i: open(base_name + to_ext(i), "wb") for i in missing}
    # only the first k survivors feed the decode plan; reading more would
    # be dead I/O (their coefficient columns are zero by construction)
    src = [i for i, p in enumerate(present) if p][:k]

    def survivor_slabs():
        for off in range(0, shard_size, slab):
            n = min(slab, shard_size - off)
            rows = []
            for i in src:
                ins[i].seek(off)
                rows.append(np.frombuffer(ins[i].read(n), dtype=np.uint8))
            yield None, np.stack(rows, axis=0)

    from ..ops import telemetry
    before = telemetry.STATS.snapshot()
    phases = {"gather": 0.0, "plan": 0.0, "dispatch": 0.0,
              "drain": 0.0, "write": 0.0}
    t_stream = time.perf_counter()
    try:
        if pipelined:
            from ..ops.pipeline import PipelinedMatmul
            t0 = time.perf_counter()
            coeffs = _rebuild_coeffs(codec, present, missing)
            phases["plan"] = time.perf_counter() - t0
            ptimer = StageTimer()
            # pieces: device-shard outputs drain and append to the
            # missing-shard files per device, no full-slab host staging
            pm = PipelinedMatmul(coeffs, max_width=slab, codec=codec,
                                 timer=ptimer, pieces=True)
            for _, _, parts in pm.stream(survivor_slabs()):
                t0 = time.perf_counter()
                for _, piece in parts:
                    for r, i in enumerate(missing):
                        outs[i].write(piece[r].tobytes())
                phases["write"] += time.perf_counter() - t0
            # consumer-side accounting: the stream loop's time splits
            # into waiting for survivor reads (gather), h2d puts
            # (dispatch), waiting for device results (drain), and the
            # writes above — overlapped worker-thread work (reader,
            # drain pool) is deliberately NOT added on top, so the
            # phases tile the wall instead of exceeding it
            phases["gather"] = ptimer.totals.get("read_wait", 0.0)
            phases["dispatch"] = ptimer.totals.get("h2d", 0.0)
            phases["drain"] = ptimer.totals.get("drain_wait", 0.0)
        else:
            for off in range(0, shard_size, slab):
                n = min(slab, shard_size - off)
                t0 = time.perf_counter()
                shards: List[Optional[np.ndarray]] = []
                for i in range(total):
                    if ins[i] is None:
                        shards.append(None)
                    else:
                        ins[i].seek(off)
                        shards.append(np.frombuffer(ins[i].read(n),
                                                    dtype=np.uint8))
                t1 = time.perf_counter()
                rebuilt = codec.reconstruct(shards)
                t2 = time.perf_counter()
                for i in missing:
                    outs[i].write(rebuilt[i].tobytes())
                t3 = time.perf_counter()
                phases["gather"] += t1 - t0
                phases["dispatch"] += t2 - t1
                phases["write"] += t3 - t2
    finally:
        for h in ins:
            if h is not None:
                h.close()
        for h in outs.values():
            h.close()
    stream_s = time.perf_counter() - t_stream
    # pad/bucket copies and dispatch issuance are the only consumer-side
    # work not bracketed above; attribute the remainder to dispatch so
    # the phase breakdown sums to the operation wall
    residual = stream_s - sum(phases.values())
    if residual > 0:
        phases["dispatch"] += residual
    for name, secs in phases.items():
        if secs > 0:
            tracing.record_span(name, secs, op="ec.rebuild",
                                backend=codec.backend)
    if stats is not None:
        stats.update(telemetry.delta(before))
        stats["survivor_bytes"] = shard_size * k
        stats["rebuilt_bytes"] = shard_size * len(missing)
        stats["stream_s"] = round(stream_s, 3)
        stats["backend"] = codec.backend
        stats["phases"] = {n: round(s, 6) for n, s in phases.items()}
    return missing


def _pb_slab(slab: int, window: int) -> int:
    """Clamp a slab size to whole windows (never below one window) so
    every stripe of a piggyback stream stays window-aligned."""
    return max(window, slab - slab % window)


def _rebuild_ec_files_piggyback(base_name, codec, layout, present,
                                missing, shard_size, slab, stats
                                ) -> List[int]:
    """Local piggyback rebuild: decode every missing shard (data AND
    parity) from the coupled decode plan's source set in one fused
    matmul per slab. Shard sizes are window-aligned by construction
    (both stripe blocks divide by the window), so slabs clamp to whole
    windows with no tail special-case."""
    import time as _time
    from ..ops import codec as ops_codec
    from ..ops import telemetry
    k = codec.k
    alpha, window = layout.alpha, layout.window
    if shard_size % window:
        raise ValueError(
            f"piggyback shard size {shard_size} not window-aligned "
            f"({window}); sidecar geometry is wrong for these shards")
    src, plan_missing, coeffs = ops_codec.piggyback_decode_plan(
        codec.k, codec.m, tuple(bool(p) for p in present),
        matrix_kind=getattr(codec, "matrix_kind", "vandermonde"),
        matrix=getattr(codec, "matrix", None),
        pairs=layout.pairs)
    rows = [plan_missing.index(i) for i in missing]
    eff_slab = _pb_slab(slab, window)
    before = telemetry.STATS.snapshot()
    phases = {"gather": 0.0, "plan": 0.0, "dispatch": 0.0,
              "drain": 0.0, "write": 0.0}
    ins = {i: open(base_name + to_ext(i), "rb") for i in src}
    outs = {i: open(base_name + to_ext(i), "wb") for i in missing}
    t_stream = _time.perf_counter()
    try:
        for off in range(0, shard_size, eff_slab):
            n = min(eff_slab, shard_size - off)
            t0 = _time.perf_counter()
            stack = []
            for i in src:
                ins[i].seek(off)
                stack.append(np.frombuffer(ins[i].read(n), dtype=np.uint8))
            block = np.stack(stack, axis=0)
            t1 = _time.perf_counter()
            sub = ops_codec.pb_split(block, alpha, window)
            out = np.asarray(codec._matmul(coeffs, sub), dtype=np.uint8)
            merged = ops_codec.pb_merge(out, alpha, window)
            t2 = _time.perf_counter()
            for r, i in zip(rows, missing):
                outs[i].write(merged[r].tobytes())
            t3 = _time.perf_counter()
            phases["gather"] += t1 - t0
            phases["dispatch"] += t2 - t1
            phases["write"] += t3 - t2
    finally:
        for h in ins.values():
            h.close()
        for h in outs.values():
            h.close()
    stream_s = _time.perf_counter() - t_stream
    for name, secs in phases.items():
        if secs > 0:
            tracing.record_span(name, secs, op="ec.rebuild",
                                backend=codec.backend, layout="piggyback")
    if stats is not None:
        stats.update(telemetry.delta(before))
        stats["survivor_bytes"] = shard_size * len(src)
        stats["rebuilt_bytes"] = shard_size * len(missing)
        stats["stream_s"] = round(stream_s, 3)
        stats["backend"] = codec.backend
        stats["layout"] = "piggyback"
        stats["phases"] = {n: round(s, 6) for n, s in phases.items()}
    return list(missing)


def rebuild_ec_files_streaming_piggyback(base_name: str,
                                         present: List[bool],
                                         missing: List[int],
                                         source,
                                         layout,
                                         codec: Optional[
                                             ReedSolomonCodec] = None,
                                         slab: int = DEFAULT_SLAB,
                                         stats: Optional[dict] = None
                                         ) -> List[int]:
    """Streaming full decode for a piggyback volume: ``source`` yields
    survivor stripes whose ROWS ARE THE DECODE PLAN'S src ORDER (every
    surviving data shard, then the plan's parity picks — the caller
    builds readers from piggyback_decode_plan's src list, not first-k).
    Each stripe is window-split, pushed through the fused coupled
    decode, merged, and appended to the missing shard files. Failure
    removes partial outputs, same contract as the flat streaming
    rebuild."""
    import time as _time
    from ..ops import codec as ops_codec
    from ..ops import telemetry
    codec = codec or get_codec(DATA_SHARDS, PARITY_SHARDS)
    if not missing:
        return []
    alpha, window = layout.alpha, layout.window
    before = telemetry.STATS.snapshot()
    phases = {"gather": 0.0, "plan": 0.0, "dispatch": 0.0,
              "drain": 0.0, "write": 0.0}
    t0 = _time.perf_counter()
    src, plan_missing, coeffs = ops_codec.piggyback_decode_plan(
        codec.k, codec.m, tuple(bool(p) for p in present),
        matrix_kind=getattr(codec, "matrix_kind", "vandermonde"),
        matrix=getattr(codec, "matrix", None),
        pairs=layout.pairs)
    rows = [plan_missing.index(i) for i in missing]
    phases["plan"] = _time.perf_counter() - t0
    outs = {i: open(base_name + to_ext(i), "wb") for i in missing}
    rebuilt_bytes = 0
    t_stream = _time.perf_counter()
    try:
        it = source.slabs()
        while True:
            t0 = _time.perf_counter()
            try:
                _, block = next(it)
            except StopIteration:
                break
            t1 = _time.perf_counter()
            sub = ops_codec.pb_split(block, alpha, window)
            out = np.asarray(codec._matmul(coeffs, sub), dtype=np.uint8)
            merged = ops_codec.pb_merge(out, alpha, window)
            t2 = _time.perf_counter()
            for r, i in zip(rows, missing):
                outs[i].write(merged[r].tobytes())
                rebuilt_bytes += merged.shape[1]
            t3 = _time.perf_counter()
            phases["gather"] += t1 - t0
            phases["dispatch"] += t2 - t1
            phases["write"] += t3 - t2
    except BaseException:
        for i, h in outs.items():
            h.close()
            try:
                os.remove(base_name + to_ext(i))
            except OSError:
                pass
        raise
    finally:
        for h in outs.values():
            h.close()
    stream_s = _time.perf_counter() - t_stream
    for name, secs in phases.items():
        if secs > 0:
            tracing.record_span(name, secs, op="ec.rebuild",
                                backend=codec.backend, streaming=True,
                                layout="piggyback")
    if stats is not None:
        gs = source.stats
        stats.update(telemetry.delta(before))
        stats.update(gs.snapshot())
        stats["survivor_bytes"] = source.shard_size * len(src)
        stats["rebuilt_bytes"] = rebuilt_bytes
        stats["stream_s"] = round(stream_s, 3)
        stats["backend"] = codec.backend
        stats["layout"] = "piggyback"
        stats["phases"] = {n: round(s, 6) for n, s in phases.items()}
        stats["gather_mbps"] = round(gs.mbps(), 1)
        stats["gather_remote_shards"] = gs.remote_shards
    return list(missing)


def rebuild_ec_files_streaming(base_name: str,
                               present: List[bool],
                               missing: List[int],
                               source,
                               codec: Optional[ReedSolomonCodec] = None,
                               slab: int = DEFAULT_SLAB,
                               pipelined: Optional[bool] = None,
                               stats: Optional[dict] = None) -> List[int]:
    """Streaming variant of rebuild_ec_files: the survivor bytes arrive
    from ``source`` (an ec.gather.StripedGatherSource — local files and
    remote holders mixed) instead of whole shard files on local disk,
    and each rebuilt slab is appended to the missing shard files as the
    decode drains. Rebuild wall approaches max(gather, compute) and the
    rebuilder never materializes a survivor copy.

    ``present``/``missing`` describe the cluster-wide shard state (the
    decode plan), not local files. On ANY failure the partially written
    missing-shard files are removed — callers either get complete
    rebuilt shards or nothing."""
    codec = codec or get_codec(DATA_SHARDS, PARITY_SHARDS)
    k, total = codec.k, codec.total
    if pipelined is None:
        pipelined = codec.backend in ("tpu", "mesh")
    if not missing:
        return []
    if sum(present) < k:
        raise ValueError(
            f"cannot rebuild: only {sum(present)} of {total} shards")
    from ..ops import telemetry
    before = telemetry.STATS.snapshot()
    phases = {"gather": 0.0, "plan": 0.0, "dispatch": 0.0,
              "drain": 0.0, "write": 0.0}
    t0 = time.perf_counter()
    coeffs = _rebuild_coeffs(codec, present, missing)
    phases["plan"] = time.perf_counter() - t0
    outs = {i: open(base_name + to_ext(i), "wb") for i in missing}
    rebuilt_bytes = 0
    t_stream = time.perf_counter()
    try:
        if pipelined:
            from ..ops.pipeline import PipelinedMatmul
            ptimer = StageTimer()
            # pieces, same as rebuild_ec_files: the sharded decode's
            # per-device outputs append as they land
            pm = PipelinedMatmul(coeffs, max_width=slab, codec=codec,
                                 timer=ptimer, pieces=True)
            for _, _, parts in pm.stream(source.slabs()):
                t0 = time.perf_counter()
                for _, piece in parts:
                    for r, i in enumerate(missing):
                        outs[i].write(piece[r].tobytes())
                        rebuilt_bytes += piece[r].nbytes
                phases["write"] += time.perf_counter() - t0
            # consumer-side accounting, same discipline as
            # rebuild_ec_files: read_wait is the time this thread spent
            # blocked on stripes still in flight — the UNOVERLAPPED
            # remainder of the gather, not its busy time
            phases["gather"] = ptimer.totals.get("read_wait", 0.0)
            phases["dispatch"] = ptimer.totals.get("h2d", 0.0)
            phases["drain"] = ptimer.totals.get("drain_wait", 0.0)
        else:
            it = source.slabs()
            while True:
                t0 = time.perf_counter()
                try:
                    _, data = next(it)
                except StopIteration:
                    break
                t1 = time.perf_counter()
                out = codec._matmul(coeffs, data)
                t2 = time.perf_counter()
                for r, i in enumerate(missing):
                    outs[i].write(np.asarray(out[r],
                                             dtype=np.uint8).tobytes())
                    rebuilt_bytes += data.shape[1]
                t3 = time.perf_counter()
                phases["gather"] += t1 - t0
                phases["dispatch"] += t2 - t1
                phases["write"] += t3 - t2
    except BaseException:
        for i, h in outs.items():
            h.close()
            try:
                os.remove(base_name + to_ext(i))
            except OSError:
                pass
        raise
    finally:
        for h in outs.values():
            h.close()
    stream_s = time.perf_counter() - t_stream
    residual = stream_s - (sum(phases.values()) - phases["plan"])
    if residual > 0:
        phases["dispatch"] += residual
    for name, secs in phases.items():
        if secs > 0:
            tracing.record_span(name, secs, op="ec.rebuild",
                                backend=codec.backend, streaming=True)
    if stats is not None:
        gs = source.stats
        stats.update(telemetry.delta(before))
        stats.update(gs.snapshot())
        stats["survivor_bytes"] = source.shard_size * k
        stats["rebuilt_bytes"] = rebuilt_bytes
        stats["stream_s"] = round(stream_s, 3)
        stats["backend"] = codec.backend
        stats["phases"] = {n: round(s, 6) for n, s in phases.items()}
        gather_busy = gs.busy_s()
        compute_busy = max(stream_s - phases["gather"], 0.0)
        serialized = gather_busy + compute_busy
        overlap = 0.0
        if serialized > 0:
            overlap = max(0.0, min(1.0,
                                   (serialized - stream_s) / serialized))
        stats["gather_busy_s"] = round(gather_busy, 3)
        stats["compute_busy_s"] = round(compute_busy, 3)
        stats["overlap_frac"] = round(overlap, 4)
        stats["gather_mbps"] = round(gs.mbps(), 1)
        stats["gather_remote_shards"] = gs.remote_shards
    return list(missing)


def _rebuild_coeffs(codec: ReedSolomonCodec, present: List[bool],
                    missing: List[int]) -> np.ndarray:
    """(len(missing), k) GF coefficients so that
    missing_rows = coeffs @ stack(first k surviving shards).

    ``missing`` may be a subset of the shards absent from ``present``:
    health-aware survivor selection masks surplus slow-holder shards
    out of the presence vector without wanting them rebuilt, so only
    the requested rows are sliced from the fused plan.

    Delegates to the codec's fused decode-plan cache (the same plan
    reconstruct() uses per-slab), so the derivation exists once —
    ops/gf256.decode_coeff_rows."""
    _, plan_missing, coeffs = codec.decode_plan(tuple(bool(p)
                                                      for p in present))
    if plan_missing == list(missing):
        return coeffs
    rows = [plan_missing.index(i) for i in missing]
    return np.ascontiguousarray(coeffs[rows])


def ec_shard_base_size(dat_size: int, large_block: int = LARGE_BLOCK_SIZE,
                       small_block: int = SMALL_BLOCK_SIZE,
                       data_shards: int = DATA_SHARDS) -> int:
    """Size every shard file will have for a given .dat size."""
    large_row = large_block * data_shards
    n_large = 0
    remaining = dat_size
    while remaining > large_row:
        n_large += 1
        remaining -= large_row
    small_row = small_block * data_shards
    n_small = (remaining + small_row - 1) // small_row
    return n_large * large_block + n_small * small_block
