"""Batched degraded-read serving tier: reconstruct-on-read as a
first-class data-plane path.

When a shard holder dies, needle reads that land on the lost shard fall
through to reconstruction. The legacy loop
(``volume_server._reconstruct_shard_range``) paid three separate taxes
per read: it fanned out to all ``TOTAL_SHARDS-1`` siblings when k
survivors suffice, it decoded the full 14-row stripe to recover one row,
and it did all of it once per request even when a hundred readers were
asking for the same dead shard at once.

``DegradedReadEngine`` serves the same contract the other way around:

* **Coalescing** — concurrent reads of the same ``(vid, lost_sid)`` are
  funneled through a per-shard leader/follower batcher. The first
  request in becomes the leader, waits ``SW_EC_DEGRADED_BATCH_MS`` for
  followers, and executes ONE gather + ONE fused decode dispatch for
  the union of their slab-aligned ranges. Everyone else just waits on a
  future — the syndrome-decoding regime where a single matmul amortizes
  across requests.
* **Exactly-k gather** — the batch fetches the decode plan's first-k
  survivor column ranges (``ops/codec.decode_plan``) through the PR-4
  reader stack: ``LocalShardReader`` for shards on this server,
  ``RemoteShardReader`` (per-stripe round-robin, ``SW_EC_HEDGE_MS``
  hedging, failover) for the rest. Never ``TOTAL_SHARDS-1`` siblings.
* **One-row decode** — ``codec.lost_row_coeffs`` extracts the lost
  shard's single coefficient row from the cached decode plan, so the
  matmul output is (1, W), not (missing, W).
* **Host/device crossover** — batches below the ``SmallDispatchTuner``
  threshold run ``host_matmul`` (a device round-trip costs more than
  the LUT walk); wider batches stream through ``PipelinedMatmul`` as a
  single fused device dispatch.
* **Slab LRU** — reconstructed slabs park in a bounded LRU
  (``SW_EC_DEGRADED_CACHE_BYTES``) keyed ``(vid, sid, slab)``, so hot
  needles on a dead shard hit memory. The store's ``on_ec_mount`` hook
  invalidates ``(vid, *)`` when shards are (re-)registered after a
  rebuild — cached slabs are bit-identical to the real shard, so the
  invalidation is about memory, not correctness, but a mounted shard
  must win immediately.

Tracing: each batch runs under an ``ec.degraded`` span with the
canonical ``plan``/``gather``/``dispatch`` phases, so degraded reads
feed the same histograms and tuner as rebuilds.
"""

from __future__ import annotations

import os
import threading
from ..util.locks import make_lock
import time
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..util import config, tracing
from .ec_volume import EcShardNotFound
from .gather import ShardSizeCache
from .transport import (GatherStats, LocalShardReader, RemoteShardReader,
                        default_hedge_ms)

CACHE_BYTES_ENV = "SW_EC_DEGRADED_CACHE_BYTES"
SLAB_BYTES_ENV = "SW_EC_DEGRADED_SLAB_BYTES"
BATCH_MS_ENV = "SW_EC_DEGRADED_BATCH_MS"
READ_TIMEOUT_ENV = "SW_EC_DEGRADED_READ_TIMEOUT_S"
MODE_ENV = "SW_EC_DEGRADED_MODE"
READAHEAD_ENV = "SW_EC_DEGRADED_READAHEAD_SLABS"

def degraded_cache_bytes() -> int:
    return max(0, config.env_int(CACHE_BYTES_ENV))


def degraded_slab_bytes() -> int:
    return max(1 << 10, config.env_int(SLAB_BYTES_ENV))


def degraded_batch_ms() -> float:
    return max(0.0, config.env_float(BATCH_MS_ENV))


def degraded_read_timeout_s() -> float:
    """Per-holder budget for degraded-read shard fetches. The legacy
    30 s meant one dead holder could eat the whole request deadline
    before failover even started; default well under it."""
    return max(0.1, config.env_float(READ_TIMEOUT_ENV))


def degraded_readahead_slabs() -> int:
    """Neighbor slabs reconstructed per batch beyond the requested
    range: the batch is already paying a gather + dispatch, so widening
    it by a slab is nearly free and sequential readers of a dead shard
    hit the LRU instead of a fresh batch. 0 disables."""
    return max(0, config.env_int(READAHEAD_ENV))


def degraded_mode() -> str:
    """"batch" (the engine) or "naive" (per-read exactly-k fallback,
    kept for A/B benching and emergencies)."""
    return (config.env_str(MODE_ENV) or "batch").strip().lower() or "batch"


class SlabCache:
    """Bounded byte-budget LRU of reconstructed slabs keyed
    ``(vid, sid, slab_idx)``. ``max_bytes == 0`` disables caching."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._entries: "OrderedDict[tuple, bytes]" = OrderedDict()
        self._bytes = 0
        self._lock = make_lock("degraded.SlabCache._lock")
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: tuple) -> Optional[bytes]:
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return hit

    def peek(self, key: tuple) -> Optional[bytes]:
        """Presence probe that counts as neither hit nor miss and does
        not touch LRU order — readahead planning must not distort the
        cache stats or promote entries it only inspects."""
        with self._lock:
            return self._entries.get(key)

    def put(self, key: tuple, data: bytes):
        if self.max_bytes <= 0 or len(data) > self.max_bytes:
            return
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._entries[key] = data
            self._bytes += len(data)
            while self._bytes > self.max_bytes:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= len(evicted)
                self.evictions += 1

    def invalidate(self, vid: int, shard_ids=None):
        sids = None if shard_ids is None else {int(s) for s in shard_ids}
        with self._lock:
            doomed = [k for k in self._entries
                      if k[0] == vid and (sids is None or k[1] in sids)]
            for k in doomed:
                self._bytes -= len(self._entries.pop(k))
        return len(doomed)

    def stats(self) -> Tuple[int, int]:
        with self._lock:
            return len(self._entries), self._bytes


class _Batch:
    """Per-(vid, sid) coalescing state. The leader flag and the pending
    slab->future map share one lock so a follower can never register
    into a batch the leader has already taken."""

    def __init__(self):
        self.lock = make_lock("degraded.Batch.lock")
        self.pending: Dict[int, "_SlabFuture"] = {}
        self.leading = False
        self.requests = 0


class _SlabFuture:
    def __init__(self):
        self._done = threading.Event()
        self._value: Optional[bytes] = None
        self._exc: Optional[BaseException] = None

    def set(self, value: bytes):
        self._value = value
        self._done.set()

    def set_exception(self, exc: BaseException):
        self._exc = exc
        self._done.set()

    def result(self, timeout: Optional[float] = None) -> bytes:
        if not self._done.wait(timeout):
            raise TimeoutError("degraded slab reconstruction timed out")
        if self._exc is not None:
            raise self._exc
        return self._value


class DegradedReadEngine:
    """Serves ``_reconstruct_shard_range`` with batching, exactly-k
    survivor gather, fused one-row decode, and a reconstructed-slab LRU.

    ``store`` supplies ``find_ec_volume``; ``locations(vid)`` returns
    the cached ``{sid: [holders]}`` map; ``loc_cache`` (optional) is the
    ``EcShardLocationCache`` to invalidate when a survivor gather dies;
    ``self_url`` (str or callable) is this server's own address, which
    never counts as a remote holder; ``codec`` (callable) resolves the
    RS codec lazily so the store's backend choice wins.
    """

    def __init__(self, store, locations, codec,
                 loc_cache=None, self_url="",
                 cache_bytes: Optional[int] = None,
                 slab: Optional[int] = None,
                 batch_ms: Optional[float] = None,
                 hedge_ms: Optional[float] = None,
                 readahead: Optional[int] = None,
                 on_read=None, on_slabs=None):
        self.store = store
        self._locations = locations
        self._codec = codec
        self._loc_cache = loc_cache
        self._self_url = self_url
        self.slab = int(slab) if slab else degraded_slab_bytes()
        self.batch_s = (degraded_batch_ms() if batch_ms is None
                        else float(batch_ms)) / 1000.0
        self._hedge_ms = hedge_ms
        self.readahead = (degraded_readahead_slabs() if readahead is None
                          else max(0, int(readahead)))
        self.cache = SlabCache(degraded_cache_bytes()
                               if cache_bytes is None else cache_bytes)
        # readahead-produced cache keys, so hits on them are attributable
        self._ra_keys: set = set()
        self.size_cache = ShardSizeCache(timeout=degraded_read_timeout_s())
        self.on_read = on_read
        # on_slabs(vid, sid, {slab_idx: bytes}) fires after every fresh
        # reconstruction — the volume server publishes the slabs into
        # the native plane's cache so the NEXT read of these bytes never
        # leaves the plane. Invalidation is paired: everything that
        # invalidates self.cache also invalidates the plane's copy.
        self.on_slabs = on_slabs
        self._lock = make_lock("degraded.Engine._lock")
        self._batches: Dict[Tuple[int, int], _Batch] = {}
        self._latencies: deque = deque(maxlen=512)
        self._c: Dict[str, int] = {
            "reads": 0, "errors": 0, "batches": 0,
            "batched_requests": 0, "last_batch_requests": 0,
            "max_batch_requests": 0, "batch_slabs": 0,
            "survivor_rows": 0, "survivor_fetches": 0,
            "survivor_bytes": 0, "remote_bytes": 0,
            "hedges_fired": 0, "hedges_won": 0, "retries": 0,
            "host_dispatches": 0, "device_dispatches": 0,
            "readahead_slabs": 0, "readahead_hits": 0,
        }
        # the gather pool is shared across batches: a batch needs at
        # most k concurrent range reads and batches for different lost
        # shards overlap under multi-failure
        self._pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="ec-degraded")

    # -- public API --------------------------------------------------------
    def read(self, vid: int, sid: int, offset: int, size: int) -> bytes:
        """Reconstructed bytes ``[offset, offset+size)`` of the lost
        shard, zero-padded past the shard tail like local reads."""
        t0 = time.perf_counter()
        try:
            out = self._read(int(vid), int(sid), int(offset), int(size))
        except Exception:
            with self._lock:
                self._c["errors"] += 1
            raise
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._c["reads"] += 1
                self._latencies.append(dt)
            if self.on_read is not None:
                try:
                    self.on_read(dt)
                except Exception:  # noqa: BLE001 - metrics must not fail reads
                    pass
        return out

    def invalidate(self, vid: int, shard_ids=None) -> int:
        """Drop cached slabs for a volume (optionally specific shards).
        Wired to ``store.on_ec_mount``: a shard re-registered after
        rebuild must be read from disk, not from the reconstruction
        cache."""
        return self.cache.invalidate(int(vid), shard_ids)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._c)
            lat = sorted(self._latencies)
        entries, nbytes = self.cache.stats()
        out["cache_hits"] = self.cache.hits
        out["cache_misses"] = self.cache.misses
        out["cache_evictions"] = self.cache.evictions
        out["cache_entries"] = entries
        out["cache_bytes"] = nbytes
        looked = out["cache_hits"] + out["cache_misses"]
        out["cache_hit_ratio"] = (out["cache_hits"] / looked) if looked \
            else 0.0
        out["readahead_hit_ratio"] = \
            (out["readahead_hits"] / out["readahead_slabs"]) \
            if out["readahead_slabs"] else 0.0
        if lat:
            out["p50_ms"] = lat[len(lat) // 2] * 1000.0
            out["p99_ms"] = lat[min(len(lat) - 1,
                                    int(len(lat) * 0.99))] * 1000.0
        else:
            out["p50_ms"] = out["p99_ms"] = 0.0
        return out

    # -- read path ---------------------------------------------------------
    def _read(self, vid: int, sid: int, offset: int, size: int) -> bytes:
        if size <= 0:
            return b""
        slab = self.slab
        first = offset // slab
        last = (offset + size - 1) // slab
        parts: Dict[int, bytes] = {}
        want: List[int] = []
        for idx in range(first, last + 1):
            key = (vid, sid, idx)
            hit = self.cache.get(key)
            if hit is None:
                want.append(idx)
            else:
                parts[idx] = hit
                with self._lock:
                    if key in self._ra_keys:
                        self._ra_keys.discard(key)
                        self._c["readahead_hits"] += 1
        if want:
            # the batch is already paying a gather + fused dispatch, so
            # widen it by the readahead window: neighbor slabs land in
            # the LRU and the next sequential read never reaches here
            ra = self.readahead if self.cache.max_bytes > 0 else 0
            extra = [idx for idx in range(last + 1, last + 1 + ra)
                     if self.cache.peek((vid, sid, idx)) is None]
            got = self._batched(vid, sid, want + extra)
            parts.update({i: got[i] for i in want})
            with self._lock:
                for idx in extra:
                    if got.get(idx):
                        self._ra_keys.add((vid, sid, idx))
                        self._c["readahead_slabs"] += 1
                if len(self._ra_keys) > 8192:  # evicted keys pile up
                    self._ra_keys.clear()
        out = bytearray()
        for idx in range(first, last + 1):
            seg = parts[idx]
            lo = max(offset, idx * slab) - idx * slab
            hi = min(offset + size, (idx + 1) * slab) - idx * slab
            piece = seg[lo:hi]
            if len(piece) < hi - lo:  # shard tail: zero-pad like local reads
                piece = piece + b"\x00" * (hi - lo - len(piece))
            out += piece
        return bytes(out)

    def _batched(self, vid: int, sid: int,
                 idxs: List[int]) -> Dict[int, bytes]:
        key = (vid, sid)
        with self._lock:
            st = self._batches.get(key)
            if st is None:
                st = self._batches[key] = _Batch()
        with st.lock:
            futs = {}
            for idx in idxs:
                f = st.pending.get(idx)
                if f is None:
                    f = st.pending[idx] = _SlabFuture()
                futs[idx] = f
            st.requests += 1
            lead = not st.leading
            if lead:
                st.leading = True
        if lead:
            if self.batch_s > 0:
                time.sleep(self.batch_s)
            with st.lock:
                take, st.pending = st.pending, {}
                nreq, st.requests = st.requests, 0
                st.leading = False
            try:
                got = self._reconstruct_batch(vid, sid,
                                              sorted(take), nreq)
                for idx, f in take.items():
                    f.set(got[idx])
            except BaseException as e:  # noqa: BLE001 - fail every waiter
                for f in take.values():
                    f.set_exception(e)
        deadline = degraded_read_timeout_s() * 3 + 30.0
        return {idx: f.result(timeout=deadline)
                for idx, f in futs.items()}

    # -- batch execution ---------------------------------------------------
    def _reconstruct_batch(self, vid: int, sid: int, idxs: List[int],
                           nreq: int) -> Dict[int, bytes]:
        with tracing.span("ec.degraded", volume=vid, shard=sid,
                          slabs=len(idxs), requests=nreq) as root:
            codec = self._codec()
            ev = self.store.find_ec_volume(vid)
            self_url = self._self_url() if callable(self._self_url) \
                else self._self_url
            locations = self._locations(vid) or {}

            present = []
            for i in range(codec.total):
                if i == sid:
                    present.append(False)
                elif ev is not None and i in ev.shards:
                    present.append(True)
                else:
                    present.append(any(h != self_url
                                       for h in locations.get(i, [])))
            if sum(present) < codec.k:
                raise EcShardNotFound(
                    f"cannot reconstruct {vid}.{sid}: only "
                    f"{sum(present)} of {codec.k} survivors reachable")
            # the volume's layout picks the decode basis: flat volumes
            # use the single lost-row coefficients over raw bytes,
            # piggyback volumes need the coupled plan's alpha sub-chunk
            # rows over window-split survivor slabs
            li = self._layout(ev, codec)
            with tracing.span("plan", backend=codec.backend,
                              layout=li.layout):
                if li.piggyback:
                    from ..ops import codec as ops_codec
                    src, pmissing, coeffs = \
                        ops_codec.piggyback_decode_plan(
                            codec.k, codec.m, tuple(present),
                            matrix_kind=getattr(codec, "matrix_kind",
                                                "vandermonde"),
                            matrix=getattr(codec, "matrix", None),
                            pairs=li.pairs)
                    pos = pmissing.index(sid)
                    row = np.ascontiguousarray(
                        coeffs[pos * li.alpha:(pos + 1) * li.alpha])
                else:
                    src, row = codec.lost_row_coeffs(tuple(present), sid)

            stats = GatherStats()
            timeout = degraded_read_timeout_s()
            readers = []
            for s in src:
                if ev is not None and s in ev.shards:
                    readers.append(LocalShardReader(ev.shards[s].path,
                                                    stats))
                else:
                    holders = [h for h in locations.get(s, [])
                               if h != self_url]
                    r = RemoteShardReader(vid, s, holders, stats,
                                          timeout=timeout,
                                          hedge_ms=self._hedge_ms)
                    r.span = root
                    readers.append(r)

            shard_size = self._shard_size(vid, ev, src, locations,
                                          self_url)
            runs = self._runs(idxs, shard_size)
            if li.piggyback:
                # the coupled transform is window-local: widen each run
                # to window boundaries (shard sizes are window-aligned
                # by construction, so the widened runs stay in range)
                runs = self._window_runs(runs, li.window, shard_size)
            try:
                blocks = self._gather(readers, runs, root)
            except Exception as e:
                # survivors we believed in are gone — drop the stale
                # location set so the next batch re-plans from fresh
                # holders rather than repeating the same dead fetch
                if self._loc_cache is not None:
                    self._loc_cache.invalidate(vid)
                raise EcShardNotFound(
                    f"survivor gather for {vid}.{sid} failed: {e}") \
                    from e

            if li.piggyback:
                out = self._dispatch_piggyback(codec, row, blocks,
                                               li.alpha, li.window)
            else:
                out = self._dispatch(codec, row, blocks)
            slabs = self._split(runs, out, shard_size)
            for idx, data in slabs.items():
                self.cache.put((vid, sid, idx), data)
            if self.on_slabs is not None:
                try:
                    self.on_slabs(vid, sid, slabs)
                except Exception:
                    pass  # publish is best-effort; the read must serve

            width = sum(w for _, w, _m in runs)
            with self._lock:
                self._c["batches"] += 1
                self._c["batched_requests"] += nreq
                self._c["last_batch_requests"] = nreq
                if nreq > self._c["max_batch_requests"]:
                    self._c["max_batch_requests"] = nreq
                self._c["batch_slabs"] += len(idxs)
                self._c["survivor_rows"] += len(readers)
                self._c["survivor_fetches"] += stats.fetches
                self._c["survivor_bytes"] += stats.bytes
                self._c["remote_bytes"] += stats.remote_bytes
                self._c["hedges_fired"] += stats.hedges_fired
                self._c["hedges_won"] += stats.hedges_won
                self._c["retries"] += stats.retries
            root.tags["bytes"] = int(width * len(readers))
            return slabs

    def _shard_size(self, vid, ev, src, locations, self_url) -> int:
        """Shard length bounds the gather: ranges are clamped to it and
        the beyond-tail remainder is zeros (every shard is equal-length,
        so any survivor's size is the lost shard's size)."""
        if ev is not None:
            for s in src:
                if s in ev.shards:
                    return ev.shards[s].size
            if ev.shards:
                return next(iter(ev.shards.values())).size
        for s in src:
            holders = [h for h in locations.get(s, []) if h != self_url]
            if holders:
                return self.size_cache.get(vid, s, holders)
        raise EcShardNotFound(f"no survivor holders to size volume {vid}")

    def _runs(self, idxs: List[int], shard_size: int
              ) -> List[Tuple[int, int, List[int]]]:
        """Merge sorted slab indices into contiguous byte ranges
        ``(off, w, member_idxs)``, clamped to the shard; a zero-width
        run marks slabs entirely past the tail (all zeros)."""
        runs: List[Tuple[int, int, List[int]]] = []
        slab = self.slab
        i = 0
        while i < len(idxs):
            j = i
            while j + 1 < len(idxs) and idxs[j + 1] == idxs[j] + 1:
                j += 1
            off = idxs[i] * slab
            end = min((idxs[j] + 1) * slab, shard_size)
            runs.append((off, max(0, end - off), idxs[i:j + 1]))
            i = j + 1
        return runs

    def _layout(self, ev, codec):
        """Resolve the volume's on-disk layout from its local sidecars;
        a server with no mounted index (ev is None) cannot be serving
        the needle lookup that led here, so flat is the safe default."""
        from ..storage.types import entry_size
        from .layout import LayoutInfo, volume_layout
        base = getattr(ev, "base_name", None)
        if base is None:
            return LayoutInfo()
        width = getattr(ev, "offset_width", None) or 4
        return volume_layout(base, codec.k, record_size=entry_size(width))

    @staticmethod
    def _window_runs(runs, window: int, shard_size: int):
        """Widen byte runs to sub-chunk window boundaries so the
        piggyback transform sees whole windows; zero-width (past-tail)
        runs stay empty."""
        out = []
        for off, w, members in runs:
            if w <= 0:
                out.append((off, w, members))
                continue
            aoff = off - off % window
            end = off + w
            aend = min(-(-end // window) * window, shard_size)
            out.append((aoff, aend - aoff, members))
        return out

    def _dispatch_piggyback(self, codec, rows: np.ndarray,
                            blocks: List[np.ndarray], alpha: int,
                            window: int) -> np.ndarray:
        """ONE coupled decode dispatch for the whole batch: window-split
        the concatenated survivor slab, multiply by the lost shard's
        alpha sub-chunk coefficient rows, and interleave the result back
        into shard bytes. Same host/device crossover as the flat path,
        measured on the sub-chunk width."""
        from ..ops.codec import (dispatch_threshold, host_matmul, pb_merge,
                                 pb_split)
        data = blocks[0] if len(blocks) == 1 else \
            np.concatenate(blocks, axis=1)
        width = data.shape[1]
        if width == 0:
            return np.zeros(0, dtype=np.uint8)
        sub = pb_split(data, alpha, window)
        thr = dispatch_threshold(codec)
        host = (not thr) or sub.shape[1] < thr
        with tracing.span("dispatch", backend=codec.backend,
                          bytes=int(data.nbytes), layout="piggyback",
                          path="host" if host else "device"):
            if host:
                out = host_matmul(rows, sub)
                with self._lock:
                    self._c["host_dispatches"] += 1
            else:
                from ..ops.pipeline import PipelinedMatmul
                pm = PipelinedMatmul(
                    rows, max_width=max(sub.shape[1], 1 << 20),
                    codec=codec)
                out = None
                for _meta, _d, o in pm.stream([(None, sub)]):
                    out = o
                with self._lock:
                    self._c["device_dispatches"] += 1
        merged = pb_merge(np.asarray(out, dtype=np.uint8), alpha, window)
        return np.ascontiguousarray(merged[0])

    def _gather(self, readers, runs, root) -> List[np.ndarray]:
        """Fetch every (survivor row x run) range concurrently; returns
        one (k, w) block per run. Exactly k rows — never more."""
        t0 = time.perf_counter()
        futs = {}
        for ri, (off, w, _m) in enumerate(runs):
            if w <= 0:
                continue
            stripe = off // self.slab
            for r, reader in enumerate(readers):
                futs[(ri, r)] = self._pool.submit(
                    reader.read, off, w, stripe)
        blocks = []
        err = None
        for ri, (off, w, _m) in enumerate(runs):
            if w <= 0:
                blocks.append(np.zeros((len(readers), 0), dtype=np.uint8))
                continue
            rows = []
            for r in range(len(readers)):
                f = futs[(ri, r)]
                if err is not None:
                    f.cancel()
                    continue
                try:
                    rows.append(np.frombuffer(f.result(), dtype=np.uint8))
                except Exception as e:  # noqa: BLE001 - drain then raise
                    err = e
            if err is None:
                blocks.append(np.stack(rows, axis=0))
        tracing.record_span("gather", time.perf_counter() - t0,
                            parent=root, op="ec.degraded",
                            bytes=sum(b.nbytes for b in blocks))
        if err is not None:
            raise err
        return blocks

    def _dispatch(self, codec, row: np.ndarray,
                  blocks: List[np.ndarray]) -> np.ndarray:
        """ONE decode dispatch for the whole batch: concatenate the
        per-run blocks into a (k, W) slab and multiply by the lost
        shard's single coefficient row. Below the small-dispatch
        crossover the host LUT walk wins; above it the batch streams
        through the device kernel."""
        from ..ops.codec import dispatch_threshold, host_matmul
        data = blocks[0] if len(blocks) == 1 else \
            np.concatenate(blocks, axis=1)
        width = data.shape[1]
        # dispatch_threshold folds the env default AND the
        # SW_EC_SMALL_DISPATCH_AUTO fitted crossover, so the tuner's
        # suggestion steers batches without reconstructing the codec
        thr = dispatch_threshold(codec)
        host = (not thr) or width < thr or width == 0
        with tracing.span("dispatch", backend=codec.backend,
                          bytes=int(data.nbytes),
                          path="host" if host else "device"):
            if host:
                out = host_matmul(row, data)
                with self._lock:
                    self._c["host_dispatches"] += 1
            else:
                from ..ops.pipeline import PipelinedMatmul
                pm = PipelinedMatmul(row, max_width=max(width, 1 << 20),
                                     codec=codec)
                out = None
                for _meta, _d, o in pm.stream([(None, data)]):
                    out = o
                with self._lock:
                    self._c["device_dispatches"] += 1
        return np.ascontiguousarray(out[0])

    def _split(self, runs: List[Tuple[int, int, List[int]]],
               out: np.ndarray, shard_size: int) -> Dict[int, bytes]:
        """Carve the decoded (W,) row back into per-slab byte strings
        in the same run order the gather concatenated them. Slabs past
        the shard tail come back empty (assembly zero-pads)."""
        slabs: Dict[int, bytes] = {}
        slab = self.slab
        pos = 0
        for off, w, members in runs:
            run_out = out[pos:pos + w]
            pos += w
            for idx in members:
                rel = idx * slab - off
                n = min(slab, max(0, shard_size - idx * slab))
                slabs[idx] = run_out[rel:rel + n].tobytes() if n else b""
        return slabs
