"""Background EC integrity scrub: verify H·x = 0 over whole shard slabs.

The syndrome check is the encode matmul with the coefficients swapped:
``codec.syndrome_plan()`` hands back the (m, k+m) parity-check rows
H = [P | I_m], and one fused (m, k+m) x (k+m, w) dispatch per slab — the
same ``PipelinedMatmul`` hot path encode and rebuild ride — proves every
byte column of the slab consistent, or pins the corrupt shard down to
the byte.  f4 (PAPER.md) treats silent on-disk decay as a routine
failure mode; this engine makes it an observable one.

Per volume server.  Paced by ``SW_EC_SCRUB_RATE_MBPS`` so a background
pass cannot starve foreground reads, idling ``SW_EC_SCRUB_IDLE_S``
between passes.  Shards the engine holds locally are read straight off
disk; the rest of the stripe is gathered from its holders through the
PR-4 reader stack (failover + hedging), so one scrubber per volume
verifies the *whole* codeword, not just its local rows.  The scrubber
for a volume is the holder of its lowest-numbered shard — a convention,
not a lease: every holder knows the shard map, so the election needs no
coordination and re-runs itself when shards move.

Scrub state (last-scrubbed, bytes verified, syndrome failures per local
shard) persists in a ``.scrub`` sidecar next to the ``.ecx``/``.ecj``
files, so a restarted server knows what is stale.  Findings flow to the
master's repair queue via the ``on_finding`` callback.
"""

import json
import os
import threading
from ..util.locks import make_lock
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..util import config, glog
from ..util import tracing
from .transport import (GatherStats, LocalShardReader, RemoteShardReader,
                        default_hedge_ms)

RATE_ENV = "SW_EC_SCRUB_RATE_MBPS"
IDLE_ENV = "SW_EC_SCRUB_IDLE_S"
SLAB_ENV = "SW_EC_SCRUB_SLAB_BYTES"

# Locating the corrupt shard from a syndrome column is O(total * m) per
# column; a handful of columns is plenty to attribute a slab.
_LOCATE_SAMPLE = 64


def scrub_rate_mbps() -> float:
    """Gather-bandwidth ceiling for a pass; 0 disables pacing."""
    return config.env_float(RATE_ENV)


def scrub_idle_s() -> float:
    """Sleep between background passes; <= 0 disables the loop (manual
    trigger via POST /admin/ec/scrub still works)."""
    return config.env_float(IDLE_ENV)


def scrub_slab_bytes() -> int:
    return max(4096, config.env_int(SLAB_ENV))


def locate_corrupt_shard(h: np.ndarray, syndrome: np.ndarray) -> int:
    """Attribute one syndrome column to a shard, or -1 if ambiguous.

    A single corrupt shard c with error byte e produces
    s_i = H[i][c] * e for every parity-check row i, so each candidate
    column of H either explains the whole syndrome (solve e from the
    first nonzero row, verify the rest) or none of it.  Multi-shard
    corruption in one byte column generally matches nothing — the slab
    is still flagged, just unattributed.
    """
    from ..ops import gf256
    m, total = h.shape
    match = -1
    for c in range(total):
        p = -1
        for i in range(m):
            if h[i][c]:
                p = i
                break
        if p < 0 or not syndrome[p]:
            continue
        e = gf256.gf_div(int(syndrome[p]), int(h[p][c]))
        if all(int(syndrome[i]) == gf256.MUL_TABLE[int(h[i][c])][e]
               for i in range(m)):
            if match >= 0:
                return -1  # two columns explain it: ambiguous
            match = c
    return match


class ScrubEngine:
    """Paced background syndrome verification of every local EC volume."""

    def __init__(self, store, locations: Callable[[int], Dict[int, list]],
                 codec: Callable[[], object],
                 self_url: Callable[[], str],
                 on_finding: Optional[Callable[[dict], bool]] = None,
                 rate_mbps: Optional[float] = None,
                 idle_s: Optional[float] = None,
                 slab: Optional[int] = None,
                 hedge_ms: Optional[float] = None):
        self.store = store
        self.locations = locations
        self.codec = codec
        self.self_url = self_url
        self.on_finding = on_finding
        self._rate_mbps = rate_mbps
        self._idle_s = idle_s
        self.slab = int(slab) if slab else scrub_slab_bytes()
        self._hedge_ms = hedge_ms
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pass_lock = make_lock("scrub._pass_lock")   # one pass at a time
        self._lock = make_lock("scrub._lock")        # counters
        self._c = {
            "passes": 0, "volumes_scrubbed": 0, "slabs": 0,
            "bytes_verified": 0, "remote_bytes": 0,
            "corrupt_slabs": 0, "corrupt_columns": 0, "findings": 0,
            "report_failures": 0, "skipped_missing": 0,
            "skipped_not_owner": 0, "errors": 0,
            "host_dispatches": 0, "device_dispatches": 0,
        }
        self._last_pass_s = 0.0
        self._last_pass_mbps = 0.0
        self._last_pass_at = 0.0
        # vid -> {"last_scrubbed":, "clean":, "corrupt_shards": [...]}
        self._volume_state: Dict[int, dict] = {}

    # -- lifecycle ---------------------------------------------------

    @property
    def rate_mbps(self) -> float:
        return self._rate_mbps if self._rate_mbps is not None \
            else scrub_rate_mbps()

    @property
    def idle_s(self) -> float:
        return self._idle_s if self._idle_s is not None else scrub_idle_s()

    def start(self):
        if self.idle_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="ec-scrub", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.idle_s):
            try:
                self.run_pass()
            except Exception as e:  # noqa: BLE001 - keep scrubbing
                glog.warning(f"ec scrub pass failed: {e}")

    # -- pass / volume -----------------------------------------------

    def run_pass(self, force: bool = False) -> dict:
        """Scrub every local EC volume this server owns (or all local
        volumes when forced).  Returns a per-pass summary."""
        with self._pass_lock:
            t0 = time.perf_counter()
            with self._lock:
                bytes0 = self._c["bytes_verified"]
            vids = self._volume_ids()
            scrubbed, findings = 0, 0
            for vid in vids:
                if self._stop.is_set():
                    break
                try:
                    res = self.scrub_volume(vid, force=force)
                except Exception as e:  # noqa: BLE001 - one volume only
                    with self._lock:
                        self._c["errors"] += 1
                    glog.warning(f"ec scrub of volume {vid} failed: {e}")
                    continue
                if res.get("skipped"):
                    continue
                scrubbed += 1
                findings += len(res.get("corrupt_shards", ()))
            dt = time.perf_counter() - t0
            with self._lock:
                self._c["passes"] += 1
                self._last_pass_s = dt
                self._last_pass_at = time.time()
                if dt > 0:
                    self._last_pass_mbps = \
                        (self._c["bytes_verified"] - bytes0) / dt / 1e6
            return {"volumes": scrubbed, "findings": findings,
                    "seconds": dt}

    def _volume_ids(self) -> List[int]:
        vids: List[int] = []
        for loc in self.store.locations:
            vids.extend(loc.ec_volumes.keys())
        return sorted(set(vids))

    def _is_owner(self, vid: int, local_sids: List[int]) -> bool:
        """One scrubber per volume: the holder of the lowest shard id
        anyone (locally or per the master's map) knows about."""
        known = set(local_sids)
        try:
            known.update(int(s) for s in (self.locations(vid) or {}))
        except Exception:  # noqa: BLE001 - location map is advisory
            pass
        return bool(known) and min(known) in local_sids

    def scrub_volume(self, vid: int, force: bool = False) -> dict:
        """Verify one volume's full codeword, slab by slab."""
        ev = self.store.find_ec_volume(vid)
        if ev is None:
            return {"volume": vid, "skipped": "not_local"}
        local = dict(ev.shards)
        if not local:
            return {"volume": vid, "skipped": "not_local"}
        local_sids = sorted(local)
        if not force and not self._is_owner(vid, local_sids):
            with self._lock:
                self._c["skipped_not_owner"] += 1
            self._set_volume_state(vid, skipped="not_owner")
            return {"volume": vid, "skipped": "not_owner"}

        codec = self.codec()
        # the volume's layout picks the parity-check rows: flat volumes
        # verify H·x=0 over raw shard bytes, piggyback volumes over the
        # sub-chunk rows ([E|I] from the coupled plan) of window-split
        # slabs — same fused dispatch, different basis
        li = self._layout(ev)
        alpha = wnd = None
        slab_eff = self.slab
        if li.piggyback:
            from ..ops import codec as ops_codec
            pplan = ops_codec.piggyback_plan(
                codec.k, codec.m,
                matrix_kind=getattr(codec, "matrix_kind", "vandermonde"),
                matrix=getattr(codec, "matrix", None),
                pairs=li.pairs)
            h = pplan.syndrome_rows()
            total = codec.total
            alpha, wnd = li.alpha, li.window
            slab_eff = max(wnd, self.slab - self.slab % wnd)
        else:
            h = codec.syndrome_plan()
            total = h.shape[1]
        gstats = GatherStats()
        readers, missing = self._readers(vid, local, total, gstats)
        if missing:
            with self._lock:
                self._c["skipped_missing"] += 1
            self._set_volume_state(vid, skipped="missing_shards",
                                   missing=missing)
            return {"volume": vid, "skipped": "missing_shards",
                    "missing": missing}

        shard_size = max(s.size for s in local.values())
        if li.piggyback and shard_size % wnd:
            # sidecar geometry disagrees with the shard bytes: a split
            # would misattribute every column, so surface it instead
            self._set_volume_state(vid, skipped="bad_geometry",
                                   window=wnd, shard_size=shard_size)
            return {"volume": vid, "skipped": "bad_geometry",
                    "window": wnd, "shard_size": shard_size}
        n_slabs = (shard_size + slab_eff - 1) // slab_eff
        corrupt_slabs: List[int] = []
        corrupt_shards: set = set()
        corrupt_columns = 0
        pass_bytes = 0
        t0 = time.perf_counter()
        gather_s = [0.0]
        dispatch_s = [0.0]

        from ..ops.codec import dispatch_threshold, host_matmul
        thr = dispatch_threshold(codec)
        use_device = bool(thr) and slab_eff >= thr

        def slabs():
            nonlocal pass_bytes
            with ThreadPoolExecutor(max_workers=min(total, 14)) as pool:
                for idx in range(n_slabs):
                    if self._stop.is_set():
                        return
                    off = idx * slab_eff
                    w = min(slab_eff, shard_size - off)
                    g0 = time.perf_counter()
                    futs = [pool.submit(readers[s].read, off, w, idx)
                            for s in range(total)]
                    rows = [np.frombuffer(f.result(), dtype=np.uint8)
                            for f in futs]
                    gather_s[0] += time.perf_counter() - g0
                    block = np.stack(rows, axis=0)
                    pass_bytes += block.nbytes
                    self._pace(t0, pass_bytes)
                    if li.piggyback:
                        from ..ops.codec import pb_split
                        block = pb_split(block, alpha, wnd)
                    yield (idx, off, w), np.ascontiguousarray(block)

        def check(meta, out):
            nonlocal corrupt_columns
            idx, off, w = meta
            bad = np.flatnonzero(out.any(axis=0))
            with self._lock:
                self._c["slabs"] += 1
                self._c["bytes_verified"] += w * total
            if not bad.size:
                return
            corrupt_slabs.append(idx)
            corrupt_columns += int(bad.size)
            with self._lock:
                self._c["corrupt_slabs"] += 1
                self._c["corrupt_columns"] += int(bad.size)
            for col in bad[:_LOCATE_SAMPLE]:
                c = locate_corrupt_shard(h, out[:, col])
                # piggyback columns live in sub-chunk space: alpha
                # consecutive columns per shard
                corrupt_shards.add(
                    c // alpha if li.piggyback and c >= 0 else c)

        with tracing.span("ec.scrub", volume=vid, shards=len(local_sids),
                          slab=slab_eff, layout=li.layout,
                          path="device" if use_device else "host") as root:
            if use_device:
                from ..ops.pipeline import PipelinedMatmul
                pm = PipelinedMatmul(h, max_width=max(slab_eff, 1 << 20),
                                     codec=codec)
                for meta, _data, out in pm.stream(slabs()):
                    d0 = time.perf_counter()
                    check(meta, np.asarray(out))
                    dispatch_s[0] += time.perf_counter() - d0
                    with self._lock:
                        self._c["device_dispatches"] += 1
            else:
                for meta, block in slabs():
                    d0 = time.perf_counter()
                    check(meta, host_matmul(h, block))
                    dispatch_s[0] += time.perf_counter() - d0
                    with self._lock:
                        self._c["host_dispatches"] += 1
            tracing.record_span("gather", gather_s[0], parent=root,
                                op="ec.scrub", bytes=pass_bytes)
            tracing.record_span("dispatch", dispatch_s[0], parent=root,
                                op="ec.scrub",
                                path="device" if use_device else "host")

        dt = time.perf_counter() - t0
        with self._lock:
            self._c["volumes_scrubbed"] += 1
            self._c["remote_bytes"] += gstats.remote_bytes
            self._last_pass_s = dt
            self._last_pass_at = time.time()
            if dt > 0:
                self._last_pass_mbps = pass_bytes / dt / 1e6
        now = time.time()
        self._persist_state(ev, local_sids, now, shard_size,
                            len(corrupt_slabs))
        clean = not corrupt_slabs
        self._set_volume_state(
            vid, last_scrubbed=now, clean=clean,
            slabs=n_slabs, corrupt_slabs=len(corrupt_slabs),
            corrupt_shards=sorted(corrupt_shards))
        res = {"volume": vid, "collection": ev.collection,
               "slabs": n_slabs, "bytes": pass_bytes,
               "seconds": dt, "clean": clean,
               "corrupt_slabs": corrupt_slabs,
               "corrupt_columns": corrupt_columns,
               "corrupt_shards": sorted(corrupt_shards)}
        if not clean:
            self._report({
                "volume": vid, "collection": ev.collection,
                "shards": sorted(s for s in corrupt_shards if s >= 0),
                "slabs": corrupt_slabs, "columns": corrupt_columns,
                "source": self.self_url(), "detected_at": now})
        return res

    def _layout(self, ev):
        """The volume's on-disk layout, resolved from its local
        sidecars (ec/layout.volume_layout)."""
        from ..storage.types import entry_size
        from .layout import volume_layout
        codec = self.codec()
        width = getattr(ev, "offset_width", None) or 4
        return volume_layout(ev.base_name, codec.k,
                             record_size=entry_size(width))

    def _readers(self, vid: int, local: Dict[int, object], total: int,
                 gstats: GatherStats) -> Tuple[list, List[int]]:
        """One reader per shard id — local shards off disk, the rest of
        the stripe from their holders.  Second return lists shard ids
        nobody can serve (lost shards are the master scan's incident,
        not a scrub finding)."""
        holders = {}
        try:
            holders = {int(s): list(u)
                       for s, u in (self.locations(vid) or {}).items()}
        except Exception:  # noqa: BLE001 - degrade to local-only view
            pass
        me = self.self_url()
        readers: list = [None] * total
        missing: List[int] = []
        hedge = self._hedge_ms if self._hedge_ms is not None \
            else default_hedge_ms()
        for sid in range(total):
            if sid in local:
                readers[sid] = LocalShardReader(local[sid].path, gstats)
                continue
            remote = [u for u in holders.get(sid, ()) if u != me]
            if not remote:
                missing.append(sid)
                continue
            readers[sid] = RemoteShardReader(vid, sid, remote, gstats,
                                             hedge_ms=hedge)
        return readers, missing

    def _pace(self, t0: float, nbytes: int):
        """Sleep enough that the pass's gather bandwidth stays under
        the configured ceiling — this is the knob that bounds scrub's
        tax on foreground p99."""
        rate = self.rate_mbps
        if rate <= 0:
            return
        ahead = nbytes / (rate * 1e6) - (time.perf_counter() - t0)
        while ahead > 0 and not self._stop.is_set():
            step = min(ahead, 0.05)
            time.sleep(step)
            ahead -= step

    # -- findings / state --------------------------------------------

    def _report(self, finding: dict):
        with self._lock:
            self._c["findings"] += 1
        cb = self.on_finding
        ok = False
        if cb is not None:
            try:
                ok = bool(cb(finding))
            except Exception as e:  # noqa: BLE001 - master may be down
                glog.warning(f"scrub finding report failed: {e}")
        if not ok:
            with self._lock:
                self._c["report_failures"] += 1

    def _persist_state(self, ev, local_sids: List[int], now: float,
                       shard_size: int, corrupt_slabs: int):
        """Durable per-shard scrub state next to the shard sidecars."""
        path = ev.base_name + ".scrub"
        state = {"shards": {}, "passes": 0}
        try:
            with open(path, "r", encoding="utf-8") as f:
                prev = json.load(f)
            if isinstance(prev, dict):
                state["shards"] = dict(prev.get("shards") or {})
                state["passes"] = int(prev.get("passes") or 0)
        except (OSError, ValueError):
            pass
        state["passes"] += 1
        for sid in local_sids:
            rec = dict(state["shards"].get(str(sid)) or {})
            rec["last_scrubbed"] = now
            rec["bytes_verified"] = \
                int(rec.get("bytes_verified") or 0) + shard_size
            rec["syndrome_failures"] = \
                int(rec.get("syndrome_failures") or 0) + corrupt_slabs
            state["shards"][str(sid)] = rec
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(state, f)
            os.replace(tmp, path)
        except OSError as e:
            glog.warning(f"scrub state write failed for {path}: {e}")

    def _set_volume_state(self, vid: int, **kw):
        with self._lock:
            self._volume_state[vid] = dict(kw)
            # drop state for volumes no longer local
            if len(self._volume_state) > 4096:
                self._volume_state.pop(next(iter(self._volume_state)))

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._c)
            out["last_pass_s"] = round(self._last_pass_s, 6)
            out["last_pass_mbps"] = round(self._last_pass_mbps, 3)
            out["last_pass_at"] = self._last_pass_at
            out["rate_mbps"] = self.rate_mbps
            out["idle_s"] = self.idle_s
            out["slab_bytes"] = self.slab
            out["volumes"] = {str(v): dict(s)
                              for v, s in self._volume_state.items()}
        return out
