"""EcVolume / EcVolumeShard — runtime EC shard access on a volume server.

Reference ec_volume.go / ec_shard.go / ec_volume_delete.go:
  * EcVolume opens .ecx (sorted index), .ecj (delete journal), .vif
    (volume info; JSON here, protobuf in the reference)
  * needle lookup is a binary search directly on the .ecx file
  * delete = tombstone the .ecx record in place + append the id to .ecj;
    rebuild_ecx_file replays the journal and removes it
  * reads resolve (offset,size) -> intervals (locate.py) -> local shard
    ReadAt or remote fetch (server layer supplies the fetcher)
"""

from __future__ import annotations

import json
import os
import struct
import threading
from ..util.locks import make_lock
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..storage.needle_map import bytes_to_entry
from ..storage.types import (NEEDLE_ENTRY_SIZE, TOMBSTONE_FILE_SIZE,
                             needle_id_to_bytes)
from .constants import (DATA_SHARDS, LARGE_BLOCK_SIZE, PARITY_SHARDS,
                        SMALL_BLOCK_SIZE, TOTAL_SHARDS, to_ext)
from .locate import Interval, locate_data


class EcShardNotFound(Exception):
    pass


def search_needle_from_sorted_index(f, file_size: int, needle_id: int,
                                    on_found: Optional[Callable] = None,
                                    offset_width: int = 4
                                    ) -> Tuple[int, int]:
    """Binary search a sorted fixed-record index stream (16B records for
    4-byte offsets, 17B for 5-byte) for needle_id. Returns
    (offset, size); on_found(file, record_pos, record_size) runs before
    return (the delete path passes the tombstoning writer). Raises
    KeyError."""
    from ..storage.types import entry_size
    rec_size = entry_size(offset_width)
    lo, hi = 0, file_size // rec_size - 1
    while lo <= hi:
        mid = (lo + hi) // 2
        f.seek(mid * rec_size)
        rec_id, offset, size = bytes_to_entry(f.read(rec_size))
        if rec_id == needle_id:
            if on_found is not None:
                on_found(f, mid * rec_size, rec_size)
            return offset, size
        if rec_id < needle_id:
            lo = mid + 1
        else:
            hi = mid - 1
    raise KeyError(needle_id)


def mark_needle_deleted(f, record_pos: int, record_size: int = 16):
    """Overwrite the Size field of the record at record_pos with the
    tombstone value (reference MarkNeedleDeleted)."""
    f.seek(record_pos + record_size - 4)  # size is the trailing 4 bytes
    f.write(struct.pack(">I", TOMBSTONE_FILE_SIZE))
    f.flush()


def ec_offset_width(base_name: str, default: int = 4) -> int:
    """The volume's index offset width, preferring the .vif sidecar
    over the .ec00 superblock. The streaming rebuilder often has NO
    local .ec00 (it pulls survivor ranges, not whole shards), so the
    .vif — which fetch_index_files copies over — must win."""
    vif = base_name + ".vif"
    if os.path.exists(vif):
        try:
            with open(vif) as f:
                width = json.load(f).get("offset_width")
            if width:
                return int(width)
        except (ValueError, OSError):
            pass
    try:
        from .decoder import read_ec_volume_superblock
        return read_ec_volume_superblock(base_name).offset_width
    except Exception:  # noqa: BLE001 - no .ec00 either
        return default


def rebuild_ecx_file(base_name: str, offset_width: int = 4):
    """Replay .ecj tombstones into .ecx, then remove the journal."""
    ecj = base_name + ".ecj"
    if not os.path.exists(ecj):
        return
    ecx_size = os.path.getsize(base_name + ".ecx")
    with open(base_name + ".ecx", "r+b") as ecx_f, open(ecj, "rb") as ecj_f:
        while True:
            rec = ecj_f.read(8)
            if len(rec) < 8:
                break
            nid = int.from_bytes(rec, "big")
            try:
                search_needle_from_sorted_index(
                    ecx_f, ecx_size, nid, mark_needle_deleted,
                    offset_width)
            except KeyError:
                pass
    os.remove(ecj)


class EcVolumeShard:
    """One .ecNN file, read-only random access."""

    def __init__(self, base_name: str, vid: int, shard_id: int,
                 collection: str = ""):
        self.base_name = base_name
        self.vid = vid
        self.shard_id = shard_id
        self.collection = collection
        self.path = base_name + to_ext(shard_id)
        self.f = open(self.path, "rb")
        self.size = os.path.getsize(self.path)

    def read_at(self, offset: int, length: int) -> bytes:
        self.f.seek(offset)
        return self.f.read(length)

    def close(self):
        self.f.close()

    def destroy(self):
        self.close()
        os.remove(self.path)


class EcVolume:
    """Mounted EC volume: local shards + the sorted index + journal."""

    def __init__(self, dirname: str, collection: str, vid: int):
        self.dir = dirname
        self.collection = collection or ""
        self.vid = vid
        name = f"{self.collection}_{vid}" if self.collection else str(vid)
        self.base_name = os.path.join(dirname, name)
        if not os.path.exists(self.base_name + ".ecx"):
            raise EcShardNotFound(f"missing {self.base_name}.ecx")
        self.ecx_file = open(self.base_name + ".ecx", "r+b")
        self.ecx_size = os.path.getsize(self.base_name + ".ecx")
        # one seekable handle shared by lookups and in-place tombstoning —
        # every seek+read/write pair must hold this lock
        self.ecx_lock = make_lock("ec_volume.ecx_lock")
        self.ecj_file = open(self.base_name + ".ecj", "a+b")
        self.ecj_lock = make_lock("ec_volume.ecj_lock")
        self.shards: Dict[int, EcVolumeShard] = {}
        self.shard_locations: Dict[int, List[str]] = {}
        self.shard_locations_lock = make_lock("ec_volume.shard_locations_lock")
        self.shard_locations_refreshed_at = 0.0
        self.created_at = time.time()
        self.version = None
        self.offset_width = None
        vif = self.base_name + ".vif"
        if os.path.exists(vif):
            try:
                with open(vif) as f:
                    info = json.load(f)
                self.version = info.get("version")
                self.offset_width = info.get("offset_width")
            except (ValueError, OSError):
                pass
        if self.version is None or self.offset_width is None:
            # no .vif: the real version+flags sit in the volume superblock,
            # which rides verbatim at the start of .ec00 (data shards hold
            # the original bytes)
            try:
                from .decoder import read_ec_volume_superblock
                sb = read_ec_volume_superblock(self.base_name)
                self.version = self.version or sb.version
                self.offset_width = self.offset_width or sb.offset_width
            except Exception:
                # last resort: defaults. Loud, not silent — a wrong
                # offset width misparses every .ecx record on this
                # holder (5B volumes), and the operator needs to know
                # to restore the .vif (ec.rebuild from a holder that
                # has it, or recreate it by hand)
                from ..util import glog
                defaulted = [f for f, val in
                             (("version", self.version),
                              ("offset_width", self.offset_width))
                             if val is None]
                self.version = self.version or 3
                self.offset_width = self.offset_width or 4
                glog.V(0).infof(
                    "ec volume %s: no usable .vif and no local data "
                    "shard; DEFAULTED %s (now version=%s "
                    "offset_width=%s) — wrong for 5-byte-offset "
                    "volumes; restore %s.vif",
                    self.base_name, ",".join(defaulted), self.version,
                    self.offset_width, self.base_name)

    # -- shard management --------------------------------------------------
    def add_shard(self, shard_id: int) -> bool:
        if shard_id in self.shards:
            return False
        self.shards[shard_id] = EcVolumeShard(
            self.base_name, self.vid, shard_id, self.collection)
        return True

    def delete_shard(self, shard_id: int) -> Optional[EcVolumeShard]:
        return self.shards.pop(shard_id, None)

    def shard_ids(self) -> List[int]:
        return sorted(self.shards)

    # -- needle lookup -----------------------------------------------------
    def locate_needle(self, needle_id: int) -> Tuple[int, int, List[Interval]]:
        """-> (dat offset, size, intervals). KeyError if absent or deleted."""
        with self.ecx_lock:
            offset, size = search_needle_from_sorted_index(
                self.ecx_file, self.ecx_size, needle_id,
                offset_width=self.offset_width)
        if size == TOMBSTONE_FILE_SIZE:
            raise KeyError(needle_id)
        from ..storage.needle import get_actual_size
        dat_size = self._dat_size_hint()
        intervals = locate_data(LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, dat_size,
                                offset, get_actual_size(size, self.version))
        return offset, size, intervals

    def _dat_size_hint(self) -> int:
        """Derive a row-accurate .dat size from a shard file size.

        shard = n_large*large + n_small*small with n_small >= 1 whenever the
        volume is non-empty (the encoder's strict `>` loop turns an exact
        final large row into small rows), so a shard size that's an exact
        multiple of the large block still means the last large-block's worth
        is small rows — the reference's +10*small fudge misreads exactly
        this case (see locate.py module docstring)."""
        shard_size = None
        for s in self.shards.values():
            shard_size = s.size
            break
        if shard_size is None:
            for i in range(TOTAL_SHARDS):
                p = self.base_name + to_ext(i)
                if os.path.exists(p):
                    shard_size = os.path.getsize(p)
                    break
        if shard_size is None:
            raise EcShardNotFound(f"no local shards for volume {self.vid}")
        n_large = shard_size // LARGE_BLOCK_SIZE
        if n_large > 0 and shard_size % LARGE_BLOCK_SIZE == 0:
            n_large -= 1
        return n_large * LARGE_BLOCK_SIZE * DATA_SHARDS + \
            (shard_size - n_large * LARGE_BLOCK_SIZE) * DATA_SHARDS

    # -- reads -------------------------------------------------------------
    def read_interval(self, interval: Interval,
                      remote_fetch: Optional[Callable] = None,
                      reconstruct_fetch: Optional[Callable] = None) -> bytes:
        """Read one interval: local shard, else remote_fetch(shard_id,
        offset, size), else reconstruction via reconstruct_fetch."""
        shard_id, off = interval.to_shard_id_and_offset(
            LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE)
        shard = self.shards.get(shard_id)
        if shard is not None:
            return shard.read_at(off, interval.size)
        if remote_fetch is not None:
            data = remote_fetch(self.vid, shard_id, off, interval.size)
            if data is not None:
                return data
        if reconstruct_fetch is not None:
            return reconstruct_fetch(self.vid, shard_id, off, interval.size)
        raise EcShardNotFound(
            f"shard {shard_id} of volume {self.vid} unavailable")

    def read_needle_blob(self, needle_id: int, remote_fetch=None,
                         reconstruct_fetch=None) -> bytes:
        _, size, intervals = self.locate_needle(needle_id)
        parts = [self.read_interval(iv, remote_fetch, reconstruct_fetch)
                 for iv in intervals]
        return b"".join(parts)

    # -- delete ------------------------------------------------------------
    def delete_needle(self, needle_id: int) -> bool:
        """Tombstone in .ecx + journal to .ecj. False if not found."""
        try:
            with self.ecx_lock:
                search_needle_from_sorted_index(
                    self.ecx_file, self.ecx_size, needle_id,
                    mark_needle_deleted, self.offset_width)
        except KeyError:
            return False
        with self.ecj_lock:
            self.ecj_file.seek(0, os.SEEK_END)
            self.ecj_file.write(needle_id_to_bytes(needle_id))
            self.ecj_file.flush()
        return True

    def write_vif(self, version: int = None):
        # merge-write: the .vif also carries the EC layout keys
        # (ec_layout/ec_window/ec_pairs, ec/layout.py) which a version
        # bump must not erase
        info = {}
        try:
            with open(self.base_name + ".vif") as f:
                info = json.load(f) or {}
        except (OSError, ValueError):
            pass
        info["version"] = version or self.version
        info["offset_width"] = self.offset_width or 4
        with open(self.base_name + ".vif", "w") as f:
            json.dump(info, f)

    def close(self):
        self.ecx_file.close()
        self.ecj_file.close()
        for s in self.shards.values():
            s.close()

    def destroy(self):
        self.close()
        for ext in (".ecx", ".ecj", ".vif", ".scrub"):
            p = self.base_name + ext
            if os.path.exists(p):
                os.remove(p)
        for i in range(TOTAL_SHARDS):
            p = self.base_name + to_ext(i)
            if os.path.exists(p):
                os.remove(p)
