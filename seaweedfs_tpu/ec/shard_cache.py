"""Tiered-freshness EC shard-location cache.

Degraded reads need to know which volume server holds each .ecNN shard.
Asking the master on every read adds an RTT per interval (and ~10 per
reconstruct), so lookups are cached per EC volume with freshness tiers
that mirror the reference (weed/storage/store_ec.go:218-259
cachedLookupEcShardLocations):

  * fewer than k shards known  -> stale after 11 s (keep retrying — the
    volume is unreadable until more holders appear)
  * every shard known          -> stale after 37 min
  * at least k known           -> stale after 7 min

plus invalidate-on-failure: a holder that fails a shard read is removed
immediately (reference forgetShardId, store_ec.go:211) so the next read
tries someone else instead of timing out again.
"""

from __future__ import annotations

import threading
from ..util.locks import make_lock
import time
from typing import Callable, Dict, List

from .constants import DATA_SHARDS, TOTAL_SHARDS

FEW_SHARDS_TTL = 11.0          # seconds, < k shards known
ALL_SHARDS_TTL = 37 * 60.0     # all shards known
ENOUGH_SHARDS_TTL = 7 * 60.0   # >= k shards known


class EcShardLocationCache:
    def __init__(self, fetch: Callable[[int], Dict[int, List[str]]],
                 data_shards: int = DATA_SHARDS,
                 total_shards: int = TOTAL_SHARDS):
        self._fetch = fetch
        self._data_shards = data_shards
        self._total_shards = total_shards
        self._lock = make_lock("shard_cache._lock")
        self._entries: Dict[int, tuple] = {}  # vid -> (refresh_t, locations)

    def _ttl(self, locations: Dict[int, List[str]]) -> float:
        known = sum(1 for urls in locations.values() if urls)
        if known < self._data_shards:
            return FEW_SHARDS_TTL
        if known >= self._total_shards:
            return ALL_SHARDS_TTL
        return ENOUGH_SHARDS_TTL

    def lookup(self, vid: int) -> Dict[int, List[str]]:
        with self._lock:
            entry = self._entries.get(vid)
            if entry is not None:
                refresh_t, locations = entry
                if time.monotonic() - refresh_t < self._ttl(locations):
                    return locations
        locations = self._fetch(vid) or {}
        with self._lock:
            self._entries[vid] = (time.monotonic(), locations)
        return locations

    def forget(self, vid: int, shard_id: int, holder: str):
        """Drop a failed holder for one shard (keeps the rest fresh)."""
        with self._lock:
            entry = self._entries.get(vid)
            if entry is None:
                return
            refresh_t, locations = entry
            urls = locations.get(shard_id)
            if urls and holder in urls:
                locations = dict(locations)
                locations[shard_id] = [u for u in urls if u != holder]
                self._entries[vid] = (refresh_t, locations)

    def invalidate(self, vid: int):
        with self._lock:
            self._entries.pop(vid, None)
