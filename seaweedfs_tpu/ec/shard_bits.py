"""ShardBits — bitmask of shard ids held by one (node, volume).

Reference ec_volume_info.go:61-113.
"""

from __future__ import annotations

from .constants import DATA_SHARDS, TOTAL_SHARDS


class ShardBits(int):
    def add_shard_id(self, sid: int) -> "ShardBits":
        return ShardBits(self | (1 << sid))

    def remove_shard_id(self, sid: int) -> "ShardBits":
        return ShardBits(self & ~(1 << sid))

    def has_shard_id(self, sid: int) -> bool:
        return bool(self & (1 << sid))

    def shard_ids(self):
        return [i for i in range(TOTAL_SHARDS) if self.has_shard_id(i)]

    def shard_id_count(self) -> int:
        return bin(self).count("1")

    def plus(self, other: "ShardBits") -> "ShardBits":
        return ShardBits(self | other)

    def minus(self, other: "ShardBits") -> "ShardBits":
        return ShardBits(self & ~other)

    def minus_parity_shards(self) -> "ShardBits":
        out = self
        for sid in range(DATA_SHARDS, TOTAL_SHARDS):
            out = out.remove_shard_id(sid)
        return out
