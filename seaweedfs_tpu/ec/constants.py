"""EC geometry constants (reference ec_encoder.go:17-23)."""

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = 14

LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1GB
SMALL_BLOCK_SIZE = 1024 * 1024         # 1MB

# the reference reads 256KB per shard per batch (ec_encoder.go:58); the TPU
# pipeline batches far larger slabs per device call — this constant remains
# only as the wire-compatible streaming granularity for shard reads
BUFFER_SIZE = 256 * 1024


def to_ext(shard_id: int) -> str:
    return f".ec{shard_id:02d}"
