"""ec — the erasure-coding pipeline (north star).

RS(10,4) striping of volumes into 14 shard files with a two-level block
layout (1GB large rows, 1MB small rows — reference
weed/storage/erasure_coding/ec_encoder.go:17-23), with the GF(2^8) compute
routed through ops.get_codec (numpy / native C++ / TPU MXU backends).
"""

from .constants import (  # noqa: F401
    DATA_SHARDS, PARITY_SHARDS, TOTAL_SHARDS,
    LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, to_ext,
)
from .encoder import write_ec_files, write_ec_files_spread, \
    write_sorted_file_from_idx, rebuild_ec_files, \
    rebuild_ec_files_streaming  # noqa: F401
from .transport import (  # noqa: F401
    GatherStats, LocalShardReader, LocalShardWriter, RemoteShardReader,
    RemoteShardWriter, SpreadError, SpreadStats, TransportStats,
)
from .gather import (  # noqa: F401
    StripedGatherSource, fetch_index_files, probe_shard_size,
)
from .spread import StripedSpreadSink, spread_window  # noqa: F401
from .locate import Interval, locate_data  # noqa: F401
