"""EC on-disk layout versioning: flat vs piggybacked sub-chunk parity.

Two layouts coexist in one cluster:

* ``flat`` — plain systematic RS; parity row j is ``a[j] @ data`` over
  whole shard bytes. Every volume written before this module existed is
  flat, and flat stays the default (``SW_EC_LAYOUT``).
* ``piggyback`` — data shards are byte-identical to flat, but parity
  shards couple paired data sub-chunks (``ops/codec.piggyback_plan``)
  so a single coupled data shard repairs from half-planes:
  ``(k+1)/(2k)`` of the k*shard full-gather download.

The layout is recorded twice, redundantly:

* the ``.vif`` JSON sidecar carries the authoritative record —
  ``ec_layout`` plus the sub-chunk geometry (``ec_window``,
  ``ec_pairs``) the repair/decode paths must agree on;
* the ``.ecx`` index gets ONE trailing version byte past the last
  sorted record (``ECX_TAG_PIGGYBACK``). Readers floor-divide the file
  size by the record width, so the tag is invisible to the binary
  search, ``walk_index_file`` and tombstone replay — but it survives
  paths that copy the .ecx without the .vif, so a rebuilder can still
  refuse to misread piggyback parity as flat.

``volume_layout`` resolves the two (``.vif`` wins) and is the single
routing predicate for store/scrub/degraded/rebuild.
"""

from __future__ import annotations

import json
import os
from typing import Optional

LAYOUT_FLAT = "flat"
LAYOUT_PIGGYBACK = "piggyback"

# trailing .ecx version byte; flat volumes carry NO tag (byte-identical
# to every pre-layout volume ever written)
ECX_TAG_PIGGYBACK = 0x01
_ECX_TAGS = {ECX_TAG_PIGGYBACK: LAYOUT_PIGGYBACK}


class LayoutInfo:
    """Resolved layout of one EC volume."""

    __slots__ = ("layout", "window", "pairs")

    def __init__(self, layout: str = LAYOUT_FLAT,
                 window: Optional[int] = None,
                 pairs: Optional[int] = None):
        self.layout = layout
        self.window = window
        self.pairs = pairs

    @property
    def piggyback(self) -> bool:
        return self.layout == LAYOUT_PIGGYBACK

    @property
    def alpha(self) -> int:
        return 1 << (self.pairs or 0)

    def __repr__(self):
        return (f"LayoutInfo({self.layout!r}, window={self.window}, "
                f"pairs={self.pairs})")


def _default_geometry(k: int) -> "tuple[int, int]":
    """(window, pairs) a volume tagged piggyback but missing its .vif
    must have been written with: the encode path only accepts the
    defaults when it writes no explicit geometry."""
    from ..ops.codec import PIGGYBACK_MAX_PAIRS
    from .constants import SMALL_BLOCK_SIZE
    return SMALL_BLOCK_SIZE, min(k // 2, PIGGYBACK_MAX_PAIRS)


def ecx_record_bytes(path: str, record_size: int) -> int:
    """Size of the record-aligned prefix of an index file — the bytes a
    copy/merge must take; anything past it is the layout tag."""
    size = os.path.getsize(path)
    return (size // record_size) * record_size


def read_ecx_tag(base_name: str, record_size: int = 16) -> Optional[str]:
    """Layout named by the trailing .ecx version byte, or None when the
    file is record-aligned (every flat/pre-layout volume)."""
    path = base_name + ".ecx"
    try:
        size = os.path.getsize(path)
    except OSError:
        return None
    extra = size % record_size
    if extra == 0:
        return None
    with open(path, "rb") as f:
        f.seek(size - 1)
        tag = f.read(1)
    return _ECX_TAGS.get(tag[0] if tag else -1)


def write_ecx_tag(base_name: str, layout: str, record_size: int = 16):
    """Append (or correct) the trailing layout byte. Flat volumes get
    NO tag — a flat .ecx must stay byte-identical to the pre-layout
    format, so marking flat means truncating back to whole records."""
    path = base_name + ".ecx"
    aligned = ecx_record_bytes(path, record_size)
    with open(path, "r+b") as f:
        f.truncate(aligned)
        if layout == LAYOUT_PIGGYBACK:
            f.seek(aligned)
            f.write(bytes([ECX_TAG_PIGGYBACK]))


def volume_layout(base_name: str, k: int,
                  record_size: int = 16) -> LayoutInfo:
    """Resolve a volume's layout from its sidecars. The .vif JSON wins;
    a bare .ecx tag falls back to the default sub-chunk geometry for
    ``k`` (the only geometry an untagged-vif encode can have written).
    No sidecar information at all means flat — exactly what every
    pre-layout volume is."""
    vif = base_name + ".vif"
    if os.path.exists(vif):
        try:
            with open(vif) as f:
                info = json.load(f)
        except (ValueError, OSError):
            info = {}
        layout = info.get("ec_layout")
        if layout == LAYOUT_PIGGYBACK:
            dw, dp = _default_geometry(k)
            return LayoutInfo(LAYOUT_PIGGYBACK,
                              int(info.get("ec_window") or dw),
                              int(info.get("ec_pairs") or dp))
        if layout:
            return LayoutInfo(LAYOUT_FLAT)
    if read_ecx_tag(base_name, record_size) == LAYOUT_PIGGYBACK:
        dw, dp = _default_geometry(k)
        return LayoutInfo(LAYOUT_PIGGYBACK, dw, dp)
    return LayoutInfo(LAYOUT_FLAT)


def write_layout_sidecars(base_name: str, layout: str,
                          window: Optional[int] = None,
                          pairs: Optional[int] = None,
                          record_size: int = 16, **vif_extra):
    """Record a volume's layout in both sidecars: merge the layout keys
    into the .vif JSON (creating it if absent) and set the .ecx tag.
    ``vif_extra`` carries the caller's other .vif fields (version,
    offset_width) so one call writes a complete sidecar."""
    vif = base_name + ".vif"
    info = {}
    if os.path.exists(vif):
        try:
            with open(vif) as f:
                info = json.load(f) or {}
        except (ValueError, OSError):
            info = {}
    info.update(vif_extra)
    info["ec_layout"] = layout
    if layout == LAYOUT_PIGGYBACK:
        info["ec_window"] = int(window)
        info["ec_pairs"] = int(pairs)
    else:
        info.pop("ec_window", None)
        info.pop("ec_pairs", None)
    with open(vif, "w") as f:
        json.dump(info, f)
    if os.path.exists(base_name + ".ecx"):
        write_ecx_tag(base_name, layout, record_size)
