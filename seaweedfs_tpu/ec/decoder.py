"""EC shard files -> volume (.ec00-09 -> .dat, .ecx+.ecj -> .idx).

Reference ec_decoder.go: decoding back to a volume is a pure interleave
copy (no GF math — data shards hold the original bytes); the .idx is the
.ecx stream plus tombstone entries replayed from the .ecj journal; the
.dat size is inferred from the maximum ecx entry end.
"""

from __future__ import annotations

import os
import shutil

from ..storage.needle import get_actual_size
from ..storage.needle_map import bytes_to_entry, entry_to_bytes
from ..util import tracing
from ..storage.super_block import SUPER_BLOCK_SIZE, SuperBlock
from ..storage.types import NEEDLE_ENTRY_SIZE, NEEDLE_ID_SIZE, \
    TOMBSTONE_FILE_SIZE, bytes_to_needle_id
from .constants import DATA_SHARDS, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, to_ext


def iterate_ecx_file(base_name: str, offset_width: int = 4):
    from ..storage.types import entry_size
    rec_size = entry_size(offset_width)
    with open(base_name + ".ecx", "rb") as f:
        while True:
            rec = f.read(rec_size)
            if len(rec) < rec_size:
                break
            yield bytes_to_entry(rec)


def iterate_ecj_file(base_name: str):
    path = base_name + ".ecj"
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            rec = f.read(NEEDLE_ID_SIZE)
            if len(rec) < NEEDLE_ID_SIZE:
                break
            yield bytes_to_needle_id(rec)


def write_idx_file_from_ec_index(base_name: str):
    """.ecx + .ecj -> .idx (reference WriteIdxFileFromEcIndex)."""
    width = read_ec_volume_superblock(base_name).offset_width
    shutil.copyfile(base_name + ".ecx", base_name + ".idx")
    with open(base_name + ".idx", "ab") as idx:
        for nid in iterate_ecj_file(base_name):
            idx.write(entry_to_bytes(nid, 0, TOMBSTONE_FILE_SIZE, width))


def read_ec_volume_superblock(base_name: str) -> SuperBlock:
    """The volume superblock rides at the start of .ec00 (data shards carry
    the original bytes verbatim) — version AND flags (offset width)."""
    with open(base_name + to_ext(0), "rb") as f:
        return SuperBlock.from_bytes(f.read(SUPER_BLOCK_SIZE))


def read_ec_volume_version(base_name: str) -> int:
    return read_ec_volume_superblock(base_name).version


def find_dat_file_size(base_name: str) -> int:
    sb = read_ec_volume_superblock(base_name)
    version = sb.version
    dat_size = 0
    for nid, offset, size in iterate_ecx_file(base_name, sb.offset_width):
        if size == TOMBSTONE_FILE_SIZE:
            continue
        end = offset + get_actual_size(size, version)
        dat_size = max(dat_size, end)
    return dat_size


def write_dat_file(base_name: str, dat_size: int,
                   large_block: int = LARGE_BLOCK_SIZE,
                   small_block: int = SMALL_BLOCK_SIZE,
                   buf_size: int = 8 << 20):
    """Interleave-copy .ec00-09 back into a .dat of dat_size bytes."""
    with tracing.span("write", op="ec.to_volume", bytes=int(dat_size)):
        _write_dat_file(base_name, dat_size, large_block, small_block,
                        buf_size)


def _write_dat_file(base_name, dat_size, large_block, small_block,
                    buf_size):
    ins = [open(base_name + to_ext(i), "rb") for i in range(DATA_SHARDS)]
    try:
        with open(base_name + ".dat", "wb") as dat:
            remaining = dat_size
            large_row = large_block * DATA_SHARDS
            block_row = 0
            while remaining > large_row:
                for i in range(DATA_SHARDS):
                    _copy_block(ins[i], block_row * large_block, large_block,
                                dat, buf_size)
                remaining -= large_row
                block_row += 1
            large_rows = block_row
            small_row_idx = 0
            small_row = small_block * DATA_SHARDS
            while remaining > 0:
                for i in range(DATA_SHARDS):
                    want = min(remaining, small_block)
                    if want <= 0:
                        break
                    _copy_block(
                        ins[i],
                        large_rows * large_block + small_row_idx * small_block,
                        want, dat, buf_size)
                    remaining -= want
                small_row_idx += 1
    finally:
        for f in ins:
            f.close()


def _copy_block(src, offset: int, length: int, dst, buf_size: int):
    src.seek(offset)
    left = length
    while left > 0:
        chunk = src.read(min(buf_size, left))
        if not chunk:
            dst.write(b"\x00" * left)
            return
        dst.write(chunk)
        left -= len(chunk)
