"""EC shard files -> volume (.ec00-09 -> .dat, .ecx+.ecj -> .idx),
plus the trace-repair combine for single-lost-shard rebuild.

Reference ec_decoder.go: decoding back to a volume is a pure interleave
copy (no GF math — data shards hold the original bytes); the .idx is the
.ecx stream plus tombstone entries replayed from the .ecj journal; the
.dat size is inferred from the maximum ecx entry end.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import List, Optional

import numpy as np

from ..storage.needle import get_actual_size
from ..storage.needle_map import bytes_to_entry, entry_to_bytes
from ..util import tracing
from ..util.profiling import StageTimer
from ..storage.super_block import SUPER_BLOCK_SIZE, SuperBlock
from ..storage.types import NEEDLE_ENTRY_SIZE, NEEDLE_ID_SIZE, \
    TOMBSTONE_FILE_SIZE, bytes_to_needle_id
from .constants import DATA_SHARDS, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, to_ext


def iterate_ecx_file(base_name: str, offset_width: int = 4):
    from ..storage.types import entry_size
    rec_size = entry_size(offset_width)
    with open(base_name + ".ecx", "rb") as f:
        while True:
            rec = f.read(rec_size)
            if len(rec) < rec_size:
                break
            yield bytes_to_entry(rec)


def iterate_ecj_file(base_name: str):
    path = base_name + ".ecj"
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            rec = f.read(NEEDLE_ID_SIZE)
            if len(rec) < NEEDLE_ID_SIZE:
                break
            yield bytes_to_needle_id(rec)


def write_idx_file_from_ec_index(base_name: str):
    """.ecx + .ecj -> .idx (reference WriteIdxFileFromEcIndex).

    Only the record-aligned prefix of the .ecx is copied: a piggyback
    volume's index carries a trailing layout version byte (ec/layout),
    and copying it would misalign every tombstone record appended
    below. The .idx format has no layout tag — the tag describes shard
    parity, and the .idx outlives the shards."""
    from ..storage.types import entry_size
    from .layout import ecx_record_bytes
    width = read_ec_volume_superblock(base_name).offset_width
    aligned = ecx_record_bytes(base_name + ".ecx", entry_size(width))
    with open(base_name + ".ecx", "rb") as src, \
            open(base_name + ".idx", "wb") as idx:
        left = aligned
        while left > 0:
            chunk = src.read(min(8 << 20, left))
            if not chunk:
                break
            idx.write(chunk)
            left -= len(chunk)
        for nid in iterate_ecj_file(base_name):
            idx.write(entry_to_bytes(nid, 0, TOMBSTONE_FILE_SIZE, width))


def read_ec_volume_superblock(base_name: str) -> SuperBlock:
    """The volume superblock rides at the start of .ec00 (data shards carry
    the original bytes verbatim) — version AND flags (offset width)."""
    with open(base_name + to_ext(0), "rb") as f:
        return SuperBlock.from_bytes(f.read(SUPER_BLOCK_SIZE))


def read_ec_volume_version(base_name: str) -> int:
    return read_ec_volume_superblock(base_name).version


def find_dat_file_size(base_name: str) -> int:
    sb = read_ec_volume_superblock(base_name)
    version = sb.version
    dat_size = 0
    for nid, offset, size in iterate_ecx_file(base_name, sb.offset_width):
        if size == TOMBSTONE_FILE_SIZE:
            continue
        end = offset + get_actual_size(size, version)
        dat_size = max(dat_size, end)
    return dat_size


def write_dat_file(base_name: str, dat_size: int,
                   large_block: int = LARGE_BLOCK_SIZE,
                   small_block: int = SMALL_BLOCK_SIZE,
                   buf_size: int = 8 << 20):
    """Interleave-copy .ec00-09 back into a .dat of dat_size bytes."""
    with tracing.span("write", op="ec.to_volume", bytes=int(dat_size)):
        _write_dat_file(base_name, dat_size, large_block, small_block,
                        buf_size)


def _write_dat_file(base_name, dat_size, large_block, small_block,
                    buf_size):
    ins = [open(base_name + to_ext(i), "rb") for i in range(DATA_SHARDS)]
    try:
        with open(base_name + ".dat", "wb") as dat:
            remaining = dat_size
            large_row = large_block * DATA_SHARDS
            block_row = 0
            while remaining > large_row:
                for i in range(DATA_SHARDS):
                    _copy_block(ins[i], block_row * large_block, large_block,
                                dat, buf_size)
                remaining -= large_row
                block_row += 1
            large_rows = block_row
            small_row_idx = 0
            small_row = small_block * DATA_SHARDS
            while remaining > 0:
                for i in range(DATA_SHARDS):
                    want = min(remaining, small_block)
                    if want <= 0:
                        break
                    _copy_block(
                        ins[i],
                        large_rows * large_block + small_row_idx * small_block,
                        want, dat, buf_size)
                    remaining -= want
                small_row_idx += 1
    finally:
        for f in ins:
            f.close()


def _copy_block(src, offset: int, length: int, dst, buf_size: int):
    src.seek(offset)
    left = length
    while left > 0:
        chunk = src.read(min(buf_size, left))
        if not chunk:
            dst.write(b"\x00" * left)
            return
        dst.write(chunk)
        left -= len(chunk)


# ---------------------------------------------------------------------------
# Trace-repair combine: the rebuilder side of bandwidth-optimal
# single-shard repair (ops/codec.repair_plan has the scheme math).
# ---------------------------------------------------------------------------

def rebuild_ec_file_repair(base_name: str, lost_sid: int, source, plan,
                           codec=None, slab: int = 8 << 20,
                           pipelined: Optional[bool] = None,
                           stats: Optional[dict] = None) -> List[int]:
    """Rebuild ONE lost shard from the trace-repair symbol stream.

    ``source`` is an ec.gather.RepairGatherSource: each stripe arrives
    as the concatenated packed symbol planes of every helper —
    ``(plan.total_bits, ceil(w/8))`` uint8. The combine matrix
    ``plan.combine`` has {0,1} coefficients, and in GF(2^8) multiplying
    by 1 is the identity while addition is XOR — so the combine IS a
    GF(2^8) matmul and the existing device kernels (PipelinedMatmul
    over the codec's device_fn) run it unchanged: one fused dispatch
    per slab, same as the full-RS decode. The 8 output planes are
    interleaved back into shard bytes on the host (a packbits
    transpose) and appended to the lost shard file.

    All-or-nothing like rebuild_ec_files_streaming: any failure removes
    the partial shard file before propagating, so the caller can fall
    back to the full streaming gather with a clean slate."""
    from ..ops import telemetry
    from ..ops.codec import combine_planes_to_bytes, get_codec
    from .constants import PARITY_SHARDS
    codec = codec or get_codec(DATA_SHARDS, PARITY_SHARDS)
    if pipelined is None:
        pipelined = codec.backend in ("tpu", "mesh")
    if lost_sid != plan.lost:
        raise ValueError(f"plan repairs shard {plan.lost}, not {lost_sid}")
    before = telemetry.STATS.snapshot()
    phases = {"gather": 0.0, "plan": 0.0, "dispatch": 0.0,
              "drain": 0.0, "write": 0.0}
    out_path = base_name + to_ext(lost_sid)
    out = open(out_path, "wb")
    rebuilt_bytes = 0
    # plane widths are byte strides: an 8 MB slab arrives as
    # total_bits x 1 MB planes, so the pipeline buckets on the stride
    stride_cap = (max(1, int(slab)) + 7) // 8
    t_stream = time.perf_counter()
    try:
        if pipelined:
            from ..ops.pipeline import PipelinedMatmul
            ptimer = StageTimer()
            pm = PipelinedMatmul(plan.combine, max_width=stride_cap,
                                 codec=codec, timer=ptimer)
            for meta, _, planes in pm.stream(source.slabs()):
                _, _, w = meta
                t0 = time.perf_counter()
                out.write(combine_planes_to_bytes(planes, w).tobytes())
                rebuilt_bytes += w
                phases["write"] += time.perf_counter() - t0
            phases["gather"] = ptimer.totals.get("read_wait", 0.0)
            phases["dispatch"] = ptimer.totals.get("h2d", 0.0)
            phases["drain"] = ptimer.totals.get("drain_wait", 0.0)
        else:
            it = source.slabs()
            while True:
                t0 = time.perf_counter()
                try:
                    meta, planes = next(it)
                except StopIteration:
                    break
                _, _, w = meta
                t1 = time.perf_counter()
                combined = codec._matmul(plan.combine, planes)
                t2 = time.perf_counter()
                out.write(combine_planes_to_bytes(
                    np.asarray(combined, dtype=np.uint8), w).tobytes())
                rebuilt_bytes += w
                t3 = time.perf_counter()
                phases["gather"] += t1 - t0
                phases["dispatch"] += t2 - t1
                phases["write"] += t3 - t2
    except BaseException:
        out.close()
        try:
            os.remove(out_path)
        except OSError:
            pass
        raise
    finally:
        if not out.closed:
            out.close()
    stream_s = time.perf_counter() - t_stream
    residual = stream_s - (sum(phases.values()) - phases["plan"])
    if residual > 0:
        phases["dispatch"] += residual
    for name, secs in phases.items():
        if secs > 0:
            tracing.record_span(name, secs, op="ec.rebuild",
                                backend=codec.backend, repair="trace")
    if stats is not None:
        gs = source.stats
        baseline = plan.k * source.shard_size
        stats.update(telemetry.delta(before))
        stats.update(gs.snapshot())
        stats["rebuilt_bytes"] = rebuilt_bytes
        stats["stream_s"] = round(stream_s, 3)
        stats["backend"] = codec.backend
        stats["phases"] = {n: round(s, 6) for n, s in phases.items()}
        gather_busy = gs.busy_s()
        compute_busy = max(stream_s - phases["gather"], 0.0)
        serialized = gather_busy + compute_busy
        overlap = 0.0
        if serialized > 0:
            overlap = max(0.0, min(1.0,
                                   (serialized - stream_s) / serialized))
        stats["gather_busy_s"] = round(gather_busy, 3)
        stats["compute_busy_s"] = round(compute_busy, 3)
        stats["overlap_frac"] = round(overlap, 4)
        stats["gather_mbps"] = round(gs.mbps(), 1)
        stats["gather_remote_shards"] = gs.remote_shards
        # the repair story: symbol bytes moved vs the k*shard baseline
        # the full-RS gather would have pulled for the same rebuild
        stats["repair_mode"] = "trace"
        stats["repair_helpers"] = len(plan.helpers)
        stats["repair_total_bits"] = plan.total_bits
        stats["repair_bits"] = {int(s): plan.bits_for(s)
                                for s in plan.helpers}
        stats["repair_bytes"] = gs.bytes
        stats["repair_remote_bytes"] = gs.remote_bytes
        stats["repair_baseline_bytes"] = baseline
        stats["repair_bytes_frac"] = round(
            gs.bytes / baseline, 4) if baseline else 0.0
        stats["repair_mbps"] = round(gs.mbps(), 1)
    return [lost_sid]


def rebuild_ec_file_piggyback(base_name: str, lost_sid: int, source,
                              rplan, window: int, codec=None,
                              slab: int = 8 << 20,
                              pipelined: Optional[bool] = None,
                              stats: Optional[dict] = None) -> List[int]:
    """Rebuild ONE coupled data shard from half-plane helper streams.

    ``source`` is an ec.gather.PlaneGatherSource: each stripe arrives
    as the restacked plane rows of every helper — k-1 data shards plus
    2 parities, ((k+1)*alpha/2, w/alpha) uint8 for a w-byte shard
    range. ``rplan.matrix`` (ops/codec.piggyback_repair_plan) turns
    that stack into the lost shard's alpha sub-chunk rows in one
    GF(2^8) matmul — the same fused kernels as the full decode — and
    pb_merge interleaves the rows back into shard bytes. Download is
    (k+1)/(2k) of the k*shard full-gather baseline: 0.55 for RS(10,4).

    All-or-nothing: any failure removes the partial shard file before
    propagating, so the caller can fall back to the full decode with a
    clean slate."""
    from ..ops import telemetry
    from ..ops.codec import get_codec, pb_merge
    from .constants import PARITY_SHARDS
    codec = codec or get_codec(DATA_SHARDS, PARITY_SHARDS)
    if pipelined is None:
        pipelined = codec.backend in ("tpu", "mesh")
    if lost_sid != rplan.lost:
        raise ValueError(f"plan repairs shard {rplan.lost}, not {lost_sid}")
    alpha = rplan.alpha
    before = telemetry.STATS.snapshot()
    phases = {"gather": 0.0, "plan": 0.0, "dispatch": 0.0,
              "drain": 0.0, "write": 0.0}
    out_path = base_name + to_ext(lost_sid)
    out = open(out_path, "wb")
    rebuilt_bytes = 0
    # stripe columns are w/alpha wide for a w-byte shard range
    stride_cap = max(1, int(slab)) // alpha + 1
    t_stream = time.perf_counter()
    try:
        if pipelined:
            from ..ops.pipeline import PipelinedMatmul
            ptimer = StageTimer()
            pm = PipelinedMatmul(rplan.matrix, max_width=stride_cap,
                                 codec=codec, timer=ptimer)
            for meta, _, sub in pm.stream(source.slabs()):
                _, _, w = meta
                t0 = time.perf_counter()
                merged = pb_merge(np.asarray(sub, dtype=np.uint8),
                                  alpha, window)
                out.write(merged[0].tobytes())
                rebuilt_bytes += w
                phases["write"] += time.perf_counter() - t0
            phases["gather"] = ptimer.totals.get("read_wait", 0.0)
            phases["dispatch"] = ptimer.totals.get("h2d", 0.0)
            phases["drain"] = ptimer.totals.get("drain_wait", 0.0)
        else:
            it = source.slabs()
            while True:
                t0 = time.perf_counter()
                try:
                    meta, stacked = next(it)
                except StopIteration:
                    break
                _, _, w = meta
                t1 = time.perf_counter()
                sub = codec._matmul(rplan.matrix, stacked)
                t2 = time.perf_counter()
                merged = pb_merge(np.asarray(sub, dtype=np.uint8),
                                  alpha, window)
                out.write(merged[0].tobytes())
                rebuilt_bytes += w
                t3 = time.perf_counter()
                phases["gather"] += t1 - t0
                phases["dispatch"] += t2 - t1
                phases["write"] += t3 - t2
    except BaseException:
        out.close()
        try:
            os.remove(out_path)
        except OSError:
            pass
        raise
    finally:
        if not out.closed:
            out.close()
    stream_s = time.perf_counter() - t_stream
    residual = stream_s - (sum(phases.values()) - phases["plan"])
    if residual > 0:
        phases["dispatch"] += residual
    for name, secs in phases.items():
        if secs > 0:
            tracing.record_span(name, secs, op="ec.rebuild",
                                backend=codec.backend, repair="piggyback")
    if stats is not None:
        gs = source.stats
        baseline = rplan.k * source.shard_size
        stats.update(telemetry.delta(before))
        stats.update(gs.snapshot())
        stats["rebuilt_bytes"] = rebuilt_bytes
        stats["stream_s"] = round(stream_s, 3)
        stats["backend"] = codec.backend
        stats["layout"] = "piggyback"
        stats["phases"] = {n: round(s, 6) for n, s in phases.items()}
        gather_busy = gs.busy_s()
        compute_busy = max(stream_s - phases["gather"], 0.0)
        serialized = gather_busy + compute_busy
        overlap = 0.0
        if serialized > 0:
            overlap = max(0.0, min(1.0,
                                   (serialized - stream_s) / serialized))
        stats["gather_busy_s"] = round(gather_busy, 3)
        stats["compute_busy_s"] = round(compute_busy, 3)
        stats["overlap_frac"] = round(overlap, 4)
        stats["gather_mbps"] = round(gs.mbps(), 1)
        stats["gather_remote_shards"] = gs.remote_shards
        # the repair story: half-plane bytes moved vs the k*shard
        # baseline the full-RS gather would have pulled
        stats["repair_mode"] = "piggyback"
        stats["repair_helpers"] = len(rplan.helpers)
        stats["repair_bytes"] = gs.bytes
        stats["repair_remote_bytes"] = gs.remote_bytes
        stats["repair_baseline_bytes"] = baseline
        stats["repair_bytes_frac"] = round(
            gs.bytes / baseline, 4) if baseline else 0.0
        stats["repair_mbps"] = round(gs.mbps(), 1)
    return [lost_sid]
