"""Interval math: logical .dat ranges -> (shard id, offset in shard file).

Faithful reimplementation of reference ec_locate.go:11-83 — the ported
TestLocateData (tests/test_ec.py) pins this arithmetic. The .dat is striped
row-major: nLargeRows rows of 10 x largeBlock first, then rows of
10 x smallBlock covering the tail; shard file i holds its block of every
row, large rows first.

Deliberate divergence from the reference: its row-count formulas
(`datSize/(10*large)` in locateOffset, the `+10*small` fudge for
LargeBlockRowsCount) disagree with its own encoder for dat sizes within
10*smallBlock of a large-row boundary — the encoder's strict
`remaining > largeRow` loop emits the boundary row as small blocks, but
locate addresses it as a large row, misreading shard bytes (a ~10MB blind
window per 10GB at production geometry). Here the large-row count is
derived exactly as the encoder does — n_large(dat) = (dat-1) // (10*large)
— so locate and layout can never disagree. The brute-force layout oracle in
tests/test_ec.py pins this for boundary sizes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from .constants import DATA_SHARDS


@dataclass(frozen=True)
class Interval:
    block_index: int
    inner_block_offset: int
    size: int
    is_large_block: bool
    large_block_rows_count: int

    def to_shard_id_and_offset(self, large_block: int, small_block: int):
        offset = self.inner_block_offset
        row = self.block_index // DATA_SHARDS
        if self.is_large_block:
            offset += row * large_block
        else:
            offset += (self.large_block_rows_count * large_block
                       + row * small_block)
        return self.block_index % DATA_SHARDS, offset


def n_large_rows_for(dat_size: int, large_block: int) -> int:
    """Number of large rows the encoder actually wrote: one per full
    10*large_block row while STRICTLY more than a row remains."""
    if dat_size <= 0:
        return 0
    return (dat_size - 1) // (large_block * DATA_SHARDS)


def _locate_offset(large_block: int, small_block: int, dat_size: int,
                   offset: int):
    large_row = large_block * DATA_SHARDS
    n_large_rows = n_large_rows_for(dat_size, large_block)
    if offset < n_large_rows * large_row:
        return offset // large_block, True, offset % large_block
    offset -= n_large_rows * large_row
    return offset // small_block, False, offset % small_block


def locate_data(large_block: int, small_block: int, dat_size: int,
                offset: int, size: int) -> List[Interval]:
    block_index, is_large, inner = _locate_offset(
        large_block, small_block, dat_size, offset)
    n_large_rows = n_large_rows_for(dat_size, large_block)

    intervals: List[Interval] = []
    while size > 0:
        block_remaining = (large_block if is_large else small_block) - inner
        take = min(size, block_remaining)
        intervals.append(Interval(block_index, inner, take, is_large,
                                  n_large_rows))
        size -= take
        if size <= 0:
            break
        block_index += 1
        if is_large and block_index == n_large_rows * DATA_SHARDS:
            is_large = False
            block_index = 0
        inner = 0
    return intervals
