"""Streaming striped shard spread for EC encode.

The copy-then-spread flow materializes all k+m shard files on the
source disk and only then lets every target pull its shards whole over
``/admin/ec/copy`` — encode wall is encode + spread, the source pays a
1.4x shard write pass plus the copy re-read, and nothing overlaps.
This module mirrors ``ec/gather.py`` on the write path: a sink that
takes the stripe stream coming out of the encode (each stripe is one
slab-aligned ``[off, off+w)`` range of every shard) and pushes each
shard's ranges straight to its assigned holder via the chunked
``/admin/ec/shard_write`` endpoint while later slabs are still
encoding. Shards bound for remote holders never touch the source disk.

Shape of the stream: ``write_stripe(data, parity)`` receives the
``(k, w)`` data rows and ``(m, w)`` parity rows of one stripe; row ``i``
is exactly the next ``w`` bytes of shard ``i``'s file. One worker per
distinct target drains a bounded send queue (``SW_EC_SPREAD_WINDOW``
stripes in flight per target), so spread memory is
O(window * (k+m) * slab), never O(volume), and each shard's ranges
arrive at its holder strictly in offset order (append-at-expected-
offset; the holder answers 409 on a mismatch).

Failure discipline:
  * every holder stages into ``<shard>.part`` and the sink finalizes
    (atomic rename) only after the full shard arrived — a failed spread
    leaves no partial shards anywhere.
  * retry: a failed send is retried once on the same target (stale
    keep-alive, transient 5xx); a 409 whose staged size already covers
    the run is treated as a delivered-but-unacked duplicate.
  * failover: a target that dies before acknowledging any byte has its
    shards re-assigned to the next free node and the in-hand run is
    replayed from offset 0. A target that dies mid-shard is not
    replayable (the earlier stripes are gone — the source never kept
    them), so the spread aborts and the shell falls back to copy mode.
"""

from __future__ import annotations

import os
import queue
import re
import threading
from ..util.locks import make_lock
import time
from typing import Dict, List, Optional, Sequence

from ..util import config, tracing
from ..util.profiling import StageTimer

DEFAULT_WINDOW = 4
SPREAD_WINDOW_ENV = "SW_EC_SPREAD_WINDOW"

_STAGED_RE = re.compile(r"staged=(\d+)")

_SENTINEL = object()


def spread_window() -> int:
    return max(1, config.env_int(SPREAD_WINDOW_ENV))


class SpreadError(Exception):
    """A shard push failed beyond what retry/failover can absorb."""


class SpreadStats:
    """Counters + busy-time accounting shared by every writer of one
    spread. Busy time is the UNION of send intervals (sends overlap
    across targets), so ``bytes / busy_s`` is the effective placement
    bandwidth, comparable to what a serialized copy phase would need."""

    def __init__(self):
        self.timer = StageTimer()
        self._lock = make_lock("spread.SpreadStats._lock")
        self.sends = 0
        self.bytes = 0
        self.retries = 0
        self.failovers = 0
        self.stripes = 0
        self.peak_buffered = 0
        self.remote_shards = 0
        self.local_shards = 0

    def add_send(self, nbytes: int, t0: float, t1: float):
        self.timer.add("spread", t1 - t0, nbytes, interval=(t0, t1))
        with self._lock:
            self.sends += 1
            self.bytes += nbytes

    def add_retry(self):
        with self._lock:
            self.retries += 1

    def add_failover(self):
        with self._lock:
            self.failovers += 1

    def busy_s(self) -> float:
        return self.timer.busy_time("spread")

    def mbps(self) -> float:
        busy = self.busy_s()
        if busy <= 0:
            return 0.0
        return self.bytes / busy / 1e6

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "spread_bytes": self.bytes,
                "spread_sends": self.sends,
                "spread_stripes": self.stripes,
                "spread_retries": self.retries,
                "spread_failovers": self.failovers,
                "peak_spread_buffer": self.peak_buffered,
            }


class LocalShardWriter:
    """Fast path for shards the source keeps: append to the local
    ``.part`` stage file, atomic-rename on finalize — the same
    no-partial-shards contract the remote protocol gives."""

    remote = False

    def __init__(self, path: str, stats: Optional[SpreadStats] = None):
        self.path = path
        self.part = path + ".part"
        self.stats = stats or SpreadStats()
        self.span = None
        self._f = None

    def send(self, url: Optional[str], off: int,
             chunks: Sequence[bytes]) -> int:
        t0 = time.perf_counter()
        if self._f is None:
            self._f = open(self.part, "wb" if off == 0 else "ab")
        if self._f.tell() != off:
            raise SpreadError(
                f"local shard write offset mismatch for {self.path}: "
                f"staged={self._f.tell()} offset={off}")
        n = 0
        for c in chunks:
            self._f.write(c)
            n += len(c)
        self.stats.add_send(n, t0, time.perf_counter())
        return n

    def finalize(self, url: Optional[str], size: int):
        if self._f is not None:
            self._f.close()
            self._f = None
        staged = os.path.getsize(self.part) if os.path.exists(self.part) \
            else -1
        if staged != size:
            raise SpreadError(
                f"local shard {self.path}: staged {staged} != {size}")
        os.replace(self.part, self.path)

    def abort(self, url: Optional[str]):
        if self._f is not None:
            self._f.close()
            self._f = None
        for p in (self.part,):
            try:
                os.remove(p)
            except OSError:
                pass


class RemoteShardWriter:
    """Pushes one shard's slab ranges to its holder: each run of
    contiguous chunks goes out as ONE chunked POST to
    ``/admin/ec/shard_write`` (append-at-expected-offset, 409 on
    mismatch), carrying the encode span's traceparent so the holder's
    spans join the encode trace."""

    remote = True

    def __init__(self, vid: int, sid: int, collection: str = "",
                 stats: Optional[SpreadStats] = None,
                 timeout: float = 300.0):
        self.vid = vid
        self.sid = sid
        self.collection = collection
        self.stats = stats or SpreadStats()
        self.span = None     # set by StripedSpreadSink: trace parent
        self.timeout = timeout

    def _url(self, holder: str, query: str) -> str:
        return (f"http://{holder}/admin/ec/shard_write?volume={self.vid}"
                f"&collection={self.collection}&shard={self.sid}&{query}")

    def _headers(self) -> Optional[dict]:
        # target worker threads don't inherit the tracing contextvar —
        # carry the encode span's traceparent explicitly
        if self.span is None:
            return None
        return {tracing.TRACEPARENT_HEADER: self.span.traceparent()}

    def send(self, url: str, off: int, chunks: Sequence[bytes]) -> int:
        from ..server.http_util import HttpError, post_chunked
        n = sum(len(c) for c in chunks)
        t0 = time.perf_counter()
        try:
            post_chunked(self._url(url, f"offset={off}"), chunks,
                         headers=self._headers(), timeout=self.timeout)
        except HttpError as e:
            if e.status == 409:
                # the holder's staged size disagrees; if it already
                # covers this run the previous delivery merely lost its
                # ack — don't re-append, don't fail
                m = _STAGED_RE.search(str(e))
                if m and int(m.group(1)) == off + n:
                    self.stats.add_send(n, t0, time.perf_counter())
                    return n
            raise
        self.stats.add_send(n, t0, time.perf_counter())
        return n

    def finalize(self, url: str, size: int):
        from ..server.http_util import http_call
        http_call("POST",
                  self._url(url, f"action=finalize&size={size}"),
                  headers=self._headers(), timeout=self.timeout)

    def abort(self, url: str):
        from ..server.http_util import http_call
        try:
            http_call("POST", self._url(url, "action=abort"),
                      headers=self._headers(), timeout=30.0)
        except Exception:  # noqa: BLE001 - best-effort cleanup
            pass


class _TargetWorker(threading.Thread):
    """Drains one target's bounded send queue: pops queued
    ``(sid, off, chunk)`` items, merges per-shard contiguous runs, and
    sends each run as one chunked POST. Owns the target url so
    failover (re-assigning every shard of a dead target to a spare)
    is a single-variable swap."""

    def __init__(self, sink: "StripedSpreadSink", url: Optional[str],
                 sids: List[int], window: int):
        name = url or "local"
        super().__init__(daemon=True, name=f"ec-spread-{name}")
        self.sink = sink
        self.url = url
        self.sids = list(sids)
        self.max_batch = max(1, window * len(sids))
        self.q: queue.Queue = queue.Queue(maxsize=self.max_batch)
        self.acked = 0
        self.error: Optional[BaseException] = None

    def run(self):
        try:
            stop = False
            while not stop:
                try:
                    item = self.q.get(timeout=0.1)
                except queue.Empty:
                    if self.sink.failed is not None:
                        return
                    continue
                batch = []
                while True:
                    if item is _SENTINEL:
                        stop = True
                        break
                    batch.append(item)
                    if len(batch) >= self.max_batch:
                        break
                    try:
                        item = self.q.get_nowait()
                    except queue.Empty:
                        break
                for sid, off, chunks in self._runs(batch):
                    n = self._send_run(sid, off, chunks)
                    self.sink._note_buffered(-n)
        except BaseException as e:  # noqa: BLE001 - surfaced to consumer
            self.error = e
            self.sink._fail(e)

    @staticmethod
    def _runs(batch):
        """Merge the batch into per-shard contiguous runs, preserving
        per-shard order (queue order is stripe order, so each shard's
        offsets arrive ascending and contiguous)."""
        runs = []          # [sid, start_off, [chunks], next_off]
        open_run: Dict[int, list] = {}
        for sid, off, chunk in batch:
            run = open_run.get(sid)
            if run is not None and run[3] == off:
                run[2].append(chunk)
                run[3] += len(chunk)
            else:
                run = [sid, off, [chunk], off + len(chunk)]
                runs.append(run)
                open_run[sid] = run
        return [(sid, off, chunks) for sid, off, chunks, _ in runs]

    def _send_run(self, sid: int, off: int, chunks) -> int:
        writer = self.sink.writers[sid]
        n = sum(len(c) for c in chunks)
        while True:
            last = None
            for attempt in range(2):
                if attempt:
                    self.sink.stats.add_retry()
                try:
                    writer.send(self.url, off, chunks)
                    self.acked += n
                    tracing.record_span(
                        "spread.run", 0.0, parent=self.sink.parent_span,
                        op="ec.encode.spread", shard=sid, offset=off,
                        bytes=n, target=self.url or "local")
                    return n
                except BaseException as e:  # noqa: BLE001 - retry/failover
                    last = e
            if self.acked > 0 or off != 0 or self.url is None:
                # bytes already landed on this target (or it's the local
                # disk): the dead holder's prefix is unreplayable — the
                # encode stream never kept it. Abort; the shell falls
                # back to the copy flow.
                raise last
            spare = self.sink._take_spare(self.url)
            if spare is None:
                raise last
            dead, self.url = self.url, spare
            self.sink.stats.add_failover()
            writer.abort(dead)


class StripedSpreadSink:
    """The placement stream: ``write_stripe`` routes each shard row of
    the arriving stripe to its holder's bounded send queue; per-target
    workers push the ranges while the encode produces the next stripes.
    ``assignment`` maps shard id -> holder url; shards mapped to
    ``local_url`` (or unmapped) take the local-writer fast path and are
    staged next to ``base_name``."""

    def __init__(self, vid: int, base_name: str,
                 assignment: Dict[int, str], total: int,
                 collection: str = "",
                 local_url: str = "",
                 spares: Optional[Sequence[str]] = None,
                 window: Optional[int] = None,
                 stats: Optional[SpreadStats] = None,
                 parent_span=None):
        from .constants import to_ext
        self.vid = vid
        self.base_name = base_name
        self.total = int(total)
        self.window = max(1, int(window) if window else spread_window())
        self.stats = stats or SpreadStats()
        self.parent_span = parent_span
        self.offset = 0
        self.failed: Optional[BaseException] = None
        self._spares = [s for s in (spares or []) if s]
        self._lock = make_lock("spread.SpreadSink._lock")
        self._buffered = 0
        self.writers: List = []
        by_target: Dict[Optional[str], List[int]] = {}
        for sid in range(self.total):
            url = assignment.get(sid) or ""
            if url == local_url:
                url = ""
            if url:
                w = RemoteShardWriter(vid, sid, collection, self.stats)
            else:
                w = LocalShardWriter(base_name + to_ext(sid), self.stats)
            w.span = parent_span
            self.writers.append(w)
            by_target.setdefault(url or None, []).append(sid)
        self.stats.remote_shards = sum(
            1 for w in self.writers if w.remote)
        self.stats.local_shards = self.total - self.stats.remote_shards
        self.workers = [
            _TargetWorker(self, url, sids, self.window)
            for url, sids in by_target.items()]
        self._worker_of = {}
        for w in self.workers:
            for sid in w.sids:
                self._worker_of[sid] = w
        self.blocked_s = 0.0     # consumer time lost to full windows
        for w in self.workers:
            w.start()

    # -- shared bookkeeping -------------------------------------------------
    def _note_buffered(self, delta: int):
        with self._lock:
            self._buffered += delta
            if self._buffered > self.stats.peak_buffered:
                self.stats.peak_buffered = self._buffered

    def _fail(self, e: BaseException):
        with self._lock:
            if self.failed is None:
                self.failed = e

    def _take_spare(self, dead: Optional[str]) -> Optional[str]:
        with self._lock:
            for i, s in enumerate(self._spares):
                if s != dead:
                    return self._spares.pop(i)
        return None

    def assignment(self) -> Dict[int, str]:
        """Final shard placement (post-failover): sid -> holder url,
        '' for shards kept on the source."""
        return {sid: (self._worker_of[sid].url or "")
                for sid in range(self.total)}

    def _put(self, worker: _TargetWorker, item):
        t0 = time.perf_counter()
        waited = False
        while True:
            if self.failed is not None:
                raise SpreadError(
                    f"shard spread failed: {self.failed!r}") \
                    from self.failed
            try:
                worker.q.put(item, timeout=0.05)
                break
            except queue.Full:
                waited = True
        if waited:
            self.blocked_s += time.perf_counter() - t0

    # -- the stream ---------------------------------------------------------
    def write_stripe(self, data, parity):
        """Route one encoded stripe: row i of ``data``/``parity`` is the
        next ``w`` bytes of shard i / shard k+i."""
        k = data.shape[0]
        w = data.shape[1]
        off = self.offset
        for sid in range(self.total):
            row = data[sid] if sid < k else parity[sid - k]
            chunk = row.tobytes()
            self._note_buffered(len(chunk))
            self._put(self._worker_of[sid], (sid, off, chunk))
        self.offset = off + w
        with self._lock:
            self.stats.stripes += 1

    def finish(self):
        """Drain every window, join the workers, then finalize all
        shards (atomic ``.part`` -> shard rename on every holder).
        Raises if any push or finalize failed."""
        t0 = time.perf_counter()
        for w in self.workers:
            self._put(w, _SENTINEL)
        for w in self.workers:
            w.join()
        self.blocked_s += time.perf_counter() - t0
        if self.failed is not None:
            raise SpreadError(
                f"shard spread failed: {self.failed!r}") from self.failed
        for sid in range(self.total):
            self.writers[sid].finalize(self._worker_of[sid].url,
                                       self.offset)

    def abort(self):
        """Stop the workers and leave no partial shards: best-effort
        ``.part`` cleanup on every holder and on the local disk."""
        self._fail(SpreadError("spread aborted"))
        for w in self.workers:
            try:
                w.q.put_nowait(_SENTINEL)
            except queue.Full:
                pass
        for w in self.workers:
            w.join(timeout=10.0)
        for sid in range(self.total):
            try:
                self.writers[sid].abort(self._worker_of[sid].url)
            except Exception:  # noqa: BLE001 - best-effort cleanup
                pass
