"""Streaming striped shard spread for EC encode — the *push* role of
``ec/transport.py``.

The copy-then-spread flow materializes all k+m shard files on the
source disk and only then lets every target pull its shards whole over
``/admin/ec/copy`` — encode wall is encode + spread, the source pays a
1.4x shard write pass plus the copy re-read, and nothing overlaps.
The streaming spread instead takes the stripe stream coming out of the
encode (each stripe is one slab-aligned ``[off, off+w)`` range of every
shard) and pushes each shard's ranges straight to its assigned holder
via the chunked ``/admin/ec/shard_write`` endpoint while later slabs
are still encoding. Shards bound for remote holders never touch the
source disk.

All of the transport — the bounded ``SW_EC_SPREAD_WINDOW`` per-target
window with peak-buffer and blocked-time accounting, contiguous-run
merging, retry/failover onto spares, first-run ``SW_EC_HEDGE_MS``
hedging, the ``.part``-stage/atomic-finalize discipline — lives in
``ec/transport.py``, shared byte-for-byte with the gather pull side.
This module keeps only what is specific to pushing an encode: mapping
a shard assignment onto transport writers.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .transport import (  # noqa: F401  - the shared transport, push role
    DEFAULT_WINDOW, _SENTINEL, _STAGED_RE, LocalShardWriter,
    RemoteShardWriter, SpreadError, SpreadStats, StripedPush,
    TransportStats, merge_runs, push_window,
)
from .transport import TargetWorker as _TargetWorker  # noqa: F401

SPREAD_WINDOW_ENV = "SW_EC_SPREAD_WINDOW"


def spread_window() -> int:
    return push_window()


class StripedSpreadSink(StripedPush):
    """The placement stream: ``write_stripe`` routes each shard row of
    the arriving stripe to its holder's bounded send queue; per-target
    workers push the ranges while the encode produces the next stripes.
    ``assignment`` maps shard id -> holder url; shards mapped to
    ``local_url`` (or unmapped) take the local-writer fast path and are
    staged next to ``base_name``. Everything after writer construction
    — windows, runs, failover, hedging, pacing, finalize/abort — is
    ``StripedPush``."""

    def __init__(self, vid: int, base_name: str,
                 assignment: Dict[int, str], total: int,
                 collection: str = "",
                 local_url: str = "",
                 spares: Optional[Sequence[str]] = None,
                 window: Optional[int] = None,
                 stats: Optional[TransportStats] = None,
                 parent_span=None,
                 rate_mbps: float = 0.0):
        from .constants import to_ext
        self.vid = vid
        self.base_name = base_name
        writers: List = []
        by_target: Dict[Optional[str], List[int]] = {}
        for sid in range(int(total)):
            url = assignment.get(sid) or ""
            if url == local_url:
                url = ""
            if url:
                w = RemoteShardWriter(vid, sid, collection)
            else:
                w = LocalShardWriter(base_name + to_ext(sid))
            writers.append(w)
            by_target.setdefault(url or None, []).append(sid)
        super().__init__(writers, by_target, spares=spares,
                         window=window, stats=stats,
                         parent_span=parent_span, rate_mbps=rate_mbps)
