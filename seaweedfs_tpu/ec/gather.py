"""Streaming striped survivor gather for EC rebuild — the *pull* role
of ``ec/transport.py``.

The copy-then-rebuild flow pulls every surviving shard whole onto the
rebuilder before the first GF byte is computed — rebuild wall is
gather + compute and the rebuilder briefly stores a full extra copy of
the volume. The streaming gather instead fetches slab-aligned byte
ranges of each survivor straight from its holders (the ranged
``/admin/ec/shard_read`` endpoint over ``http_util``'s keep-alive
pool) and hands each arriving stripe to the pipelined decode while the
next stripes are still in flight.

All of the transport — the bounded ``SW_EC_GATHER_WINDOW`` in-flight
window with peak-buffer accounting, per-holder rotation, failover,
``SW_EC_HEDGE_MS`` hedging with loser-drain health attribution, local
fast paths — lives in ``ec/transport.py``, shared byte-for-byte with
the spread push side. This module keeps only what is specific to
pulling shards: shard-size probing, index-sidecar fetching, and the
trace-repair projection readers/stream shape.
"""

from __future__ import annotations

import os
import re
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..util.locks import make_lock
from .transport import (  # noqa: F401  - the shared transport, pull role
    DEFAULT_WINDOW, HEDGE_MS_ENV, GatherStats, LocalShardReader,
    RemoteShardReader, StripedPull, TransportStats, default_hedge_ms,
    hedge_pool, pull_window,
)

GATHER_WINDOW_ENV = "SW_EC_GATHER_WINDOW"

# old private name — tests and older callers reach for it
_hedge_pool = hedge_pool

_CONTENT_RANGE_RE = re.compile(r"bytes\s+(\d+)-(\d+)/(\d+)")


def auto_slab(shard_size: int, default: int = 8 << 20,
              min_slab: int = 1 << 20, target_stripes: int = 4) -> int:
    """Slab size for a rebuild when the caller didn't pick one. The
    default 8 MB slab is right for volume-scale shards, but a shard
    smaller than ~one slab degenerates to a single stripe — nothing for
    the gather to overlap with the decode. Shrink the slab (never below
    ``min_slab``) so the stream has at least ``target_stripes`` stripes;
    truly tiny shards keep the default (one stripe — pipelining dust
    costs more than it saves)."""
    if shard_size <= 2 * min_slab:
        return default
    per = -(-shard_size // target_stripes)
    return max(min_slab, min(default, per))


def gather_window() -> int:
    return pull_window()


def probe_shard_size(vid: int, sid: int, holders: Sequence[str],
                     timeout: float = 30.0) -> int:
    """Total shard size via a one-byte suffix-range read: the 206's
    ``Content-Range: bytes a-b/total`` carries the full size without
    transferring the shard (and exercises the ``bytes=-N`` path).

    A holder that rejects the suffix form with 416 (strict servers do
    for some edge encodings) falls back to sizing the shard with
    1-byte ``offset=`` reads — double the offset until EOF, then
    binary-search the boundary: ~2*log2(size) tiny requests instead of
    transferring (or asking the holder to buffer) the whole shard."""
    from ..server.http_util import HttpError, http_call, \
        http_get_with_headers

    def _size_by_tiny_reads(url: str) -> int:
        def has_byte(off: int) -> bool:
            data = http_call("GET", url + f"&offset={off}&size=1",
                             timeout=timeout)
            return len(data) > 0

        if not has_byte(0):
            return 0
        lo, hi = 0, 1
        while has_byte(hi):
            lo, hi = hi, hi * 2
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if has_byte(mid):
                lo = mid
            else:
                hi = mid
        return lo + 1

    last = None
    for holder in holders:
        url = (f"http://{holder}/admin/ec/shard_read?volume={vid}"
               f"&shard={sid}")
        try:
            _, hdrs = http_get_with_headers(
                url, timeout=timeout, headers={"Range": "bytes=-1"})
        except HttpError as e:
            if e.status == 416:
                try:
                    return _size_by_tiny_reads(url)
                except HttpError as e2:
                    last = e2
                    continue
            last = e
            continue
        cr = next((v for k, v in hdrs.items()
                   if k.lower() == "content-range"), "")
        m = _CONTENT_RANGE_RE.match(cr or "")
        if m:
            return int(m.group(3))
        last = HttpError(
            502, f"no Content-Range from {holder} for {vid}.{sid}")
    if last is not None:
        raise last
    raise ValueError(f"shard {vid}.{sid}: no holders to probe")


class ShardSizeCache:
    """Per-rebuild memo of ``probe_shard_size`` keyed by (vid, sid).

    Trace repair sizes the lost shard off whichever survivor answers
    first, and a multi-volume rebuild touches the same survivors
    repeatedly — one suffix probe per shard per rebuild is enough.
    ``probes`` counts actual wire probes so tests can assert the memo
    held."""

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout
        self.probes = 0
        self._sizes: Dict[Tuple[int, int], int] = {}
        self._lock = make_lock("gather.ShardSizeCache._lock")

    def get(self, vid: int, sid: int, holders: Sequence[str]) -> int:
        key = (int(vid), int(sid))
        with self._lock:
            if key in self._sizes:
                return self._sizes[key]
        size = probe_shard_size(vid, sid, holders, timeout=self.timeout)
        with self._lock:
            self.probes += 1
            self._sizes[key] = size
        return size


class RemoteRepairReader(RemoteShardReader):
    """Projected reads for trace repair: asks the holder to apply this
    survivor's GF(2^8) trace masks server-side and ship only the packed
    symbol planes — ``len(masks) * ceil(n/8)`` bytes for an n-byte
    range. Rotation, failover and hedging come from the shared
    transport reader."""

    _method = "POST"
    _health_kind = "repair_read"

    def __init__(self, vid: int, sid: int, holders: Sequence[str],
                 masks: Sequence[int],
                 stats: Optional[TransportStats] = None,
                 timeout: float = 300.0,
                 hedge_ms: Optional[float] = None):
        super().__init__(vid, sid, holders, stats=stats, timeout=timeout,
                         hedge_ms=hedge_ms)
        if not masks:
            raise ValueError(f"shard {vid}.{sid}: no repair masks")
        self.masks = [int(x) for x in masks]

    def _url(self, holder: str, off: int, n: int) -> str:
        m = ",".join(str(x) for x in self.masks)
        return (f"http://{holder}/admin/ec/shard_repair_read"
                f"?volume={self.vid}&shard={self.sid}"
                f"&offset={off}&size={n}&masks={m}")

    def _expect_len(self, n: int) -> int:
        return len(self.masks) * ((n + 7) // 8)


class LocalRepairReader:
    """Trace projection of a survivor shard already on the rebuilder's
    disk: read the range locally, project, and account only the symbol
    bytes (the range itself never crossed the network)."""

    remote = False

    def __init__(self, path: str, masks: Sequence[int],
                 stats: Optional[TransportStats] = None):
        if not masks:
            raise ValueError(f"{path}: no repair masks")
        self.path = path
        self.masks = [int(x) for x in masks]
        self.stats = stats or GatherStats()

    def read(self, off: int, n: int, stripe_idx: int = 0) -> bytes:
        from ..ops.codec import project_slab
        t0 = time.perf_counter()
        with open(self.path, "rb") as f:
            f.seek(off)
            data = f.read(n)
        if len(data) != n:
            raise IOError(f"short read of {self.path} at {off}: "
                          f"{len(data)} < {n}")
        planes = project_slab(np.frombuffer(data, dtype=np.uint8),
                              self.masks)
        self.stats.add_fetch(planes.nbytes, t0, time.perf_counter())
        return planes.tobytes()


class RemotePlaneReader(RemoteShardReader):
    """Half-plane reads for piggyback repair: asks the holder to apply
    the sub-chunk plane selection server-side (ops/codec.pb_plane_slice)
    and ship only the lost shard's repair plane — ``n/2`` bytes for an
    n-byte window-aligned range. Rotation, failover and hedging come
    from the shared transport reader."""

    _method = "POST"
    _health_kind = "plane_read"

    def __init__(self, vid: int, sid: int, holders: Sequence[str],
                 alpha: int, window: int, plane_bit: int, plane_side: int,
                 stats: Optional[TransportStats] = None,
                 timeout: float = 300.0,
                 hedge_ms: Optional[float] = None):
        super().__init__(vid, sid, holders, stats=stats, timeout=timeout,
                         hedge_ms=hedge_ms)
        self.alpha = int(alpha)
        self.window = int(window)
        self.plane_bit = int(plane_bit)
        self.plane_side = int(plane_side)

    def _url(self, holder: str, off: int, n: int) -> str:
        return (f"http://{holder}/admin/ec/shard_plane_read"
                f"?volume={self.vid}&shard={self.sid}"
                f"&offset={off}&size={n}&alpha={self.alpha}"
                f"&window={self.window}&bit={self.plane_bit}"
                f"&side={self.plane_side}")

    def _expect_len(self, n: int) -> int:
        return n // 2


class LocalPlaneReader:
    """Plane slice of a helper shard already on the rebuilder's disk:
    read the window-aligned range locally, slice the repair plane, and
    account only the plane bytes (the range never crossed the
    network)."""

    remote = False

    def __init__(self, path: str, alpha: int, window: int,
                 plane_bit: int, plane_side: int,
                 stats: Optional[TransportStats] = None):
        self.path = path
        self.alpha = int(alpha)
        self.window = int(window)
        self.plane_bit = int(plane_bit)
        self.plane_side = int(plane_side)
        self.stats = stats or GatherStats()

    def read(self, off: int, n: int, stripe_idx: int = 0) -> bytes:
        from ..ops.codec import pb_plane_slice
        t0 = time.perf_counter()
        with open(self.path, "rb") as f:
            f.seek(off)
            data = f.read(n)
        if len(data) != n:
            raise IOError(f"short read of {self.path} at {off}: "
                          f"{len(data)} < {n}")
        plane = pb_plane_slice(np.frombuffer(data, dtype=np.uint8),
                               self.alpha, self.window,
                               self.plane_bit, self.plane_side)
        self.stats.add_fetch(plane.nbytes, t0, time.perf_counter())
        return plane.tobytes()


def fetch_index_files(base_name: str, holders: Sequence[str],
                      timeout: float = 300.0) -> List[str]:
    """Pull the small index sidecars onto the rebuilder: .ecx required
    (the rebuilt .ecx tombstone replay and the mount need it), .vif and
    .ecj best-effort. These are KB-sized — the only whole files the
    streaming rebuild copies."""
    from ..server.http_util import HttpError, http_call
    name = os.path.basename(base_name)
    fetched: List[str] = []
    for ext, required in ((".ecx", True), (".vif", False), (".ecj", False)):
        if os.path.exists(base_name + ext):
            continue
        last = None
        data = None
        for holder in holders:
            try:
                data = http_call(
                    "GET",
                    f"http://{holder}/admin/file?name={name}{ext}",
                    timeout=timeout)
                break
            except HttpError as e:
                last = e
                data = None
        if data is None:
            if required:
                raise last if last is not None else HttpError(
                    404, f"{name}{ext}: no holder serves it")
            continue
        with open(base_name + ext, "wb") as f:
            f.write(data)
        fetched.append(ext)
    return fetched


class StripedGatherSource(StripedPull):
    """The survivor stream: ``slabs()`` yields ``(meta, (k, w) uint8)``
    stripes in order, fetching up to ``window`` stripes ahead.
    ``readers`` are the first-k survivors in decode plan order — local
    files and remote holders mixed freely. Pure transport: the window,
    pool, ordering, rotation, failover and hedging all come from
    ``StripedPull``."""


class RepairGatherSource(StripedPull):
    """Trace-repair symbol stream: the readers are one projection
    reader per plan helper (``ops/codec.RepairPlan`` order), each
    returning its packed symbol planes for the stripe range. ``slabs()``
    yields ``(meta, (total_bits, ceil(w/8)) uint8)`` blocks — the
    concatenated planes in helper-then-mask order, ready for the fused
    combine matmul. The bounded window, round-robin rotation, failover
    and hedging all come from the shared transport; only the stripe
    shape and memory accounting differ."""

    def __init__(self, readers: Sequence, shard_size: int, plan,
                 slab: int = 8 << 20, window: Optional[int] = None,
                 stats: Optional[TransportStats] = None,
                 parent_span=None):
        if len(readers) != len(plan.helpers):
            raise ValueError(
                f"need one reader per helper: {len(readers)} != "
                f"{len(plan.helpers)}")
        super().__init__(readers, shard_size, slab=slab, window=window,
                         stats=stats, parent_span=parent_span)
        self.plan = plan

    def _stripe_nbytes(self, w: int) -> int:
        return self.plan.total_bits * ((w + 7) // 8)

    def _assemble(self, bufs: List[bytes], w: int) -> np.ndarray:
        stride = (w + 7) // 8
        rows = [np.frombuffer(b, dtype=np.uint8).reshape(-1, stride)
                for b in bufs]
        return np.concatenate(rows, axis=0)


class PlaneGatherSource(StripedPull):
    """Piggyback-repair plane stream: the readers are one plane reader
    per plan helper (``ops/codec.PiggybackRepairPlan.helpers`` order —
    k-1 data shards then the 2 parities), each returning its half-plane
    bytes for the stripe range. ``slabs()`` yields
    ``(meta, ((k+1)*alpha/2, w/alpha) uint8)`` blocks — the restacked
    plane rows in plan column order, ready for the fused repair matmul.
    Stripes are clamped to sub-chunk windows so every holder-side slice
    and rebuilder-side restack is window-local."""

    def __init__(self, readers: Sequence, shard_size: int, plan,
                 window: int, slab: int = 8 << 20,
                 gather_window: Optional[int] = None,
                 stats: Optional[TransportStats] = None,
                 parent_span=None):
        if len(readers) != len(plan.helpers):
            raise ValueError(
                f"need one reader per helper: {len(readers)} != "
                f"{len(plan.helpers)}")
        if shard_size % window:
            raise ValueError(
                f"piggyback shard size {shard_size} not aligned to "
                f"window {window}")
        slab = max(window, slab - slab % window)
        super().__init__(readers, shard_size, slab=slab,
                         window=gather_window, stats=stats,
                         parent_span=parent_span)
        self.plan = plan
        self.pb_window = int(window)

    def _stripe_nbytes(self, w: int) -> int:
        return len(self.readers) * (w // 2)

    def _assemble(self, bufs: List[bytes], w: int) -> np.ndarray:
        from ..ops.codec import pb_plane_rows
        rows = [pb_plane_rows(np.frombuffer(b, dtype=np.uint8),
                              self.plan.alpha, self.pb_window)
                for b in bufs]
        return np.concatenate(rows, axis=0)
