"""Streaming striped survivor gather for EC rebuild.

The copy-then-rebuild flow pulls every surviving shard whole onto the
rebuilder before the first GF byte is computed — rebuild wall is
gather + compute and the rebuilder briefly stores a full extra copy of
the volume. This module replaces the gather side: a slab-granular
source that fetches slab-aligned byte ranges of each survivor straight
from its holders (the existing ranged ``/admin/ec/shard_read``
endpoint, over ``http_util``'s keep-alive pool) and hands each arriving
stripe to the pipelined decode while the next stripes are still in
flight.

Shape of the stream: a *stripe* is one slab-aligned range
``[off, off+w)`` of every chosen survivor — a ``(k, w)`` uint8 block,
exactly what ``ops/pipeline.PipelinedMatmul`` consumes. Stripes are
fetched with a bounded in-flight window (``SW_EC_GATHER_WINDOW``), so
gather memory is O(window · k · slab), never O(volume), and yielded
strictly in stripe order so the decoded slabs append to the rebuilt
shard files in place.

Straggler defenses:
  * round-robin: when a shard has several replicas, stripe ``s`` leads
    with holder ``s % len(holders)`` — consecutive stripes split across
    the replicas instead of hammering one.
  * retry: a failed range read fails over to the shard's remaining
    holders in rotation order.
  * hedging (``SW_EC_HEDGE_MS``, default off): if the leading holder
    has not answered within the deadline, the same range is requested
    from the next holder and the first response wins. The loser is NOT
    cancelled — ``http_call`` reads its response to completion, so the
    socket drains and parks back in the pool instead of leaking
    mid-body.
"""

from __future__ import annotations

import os
import re
import threading
from ..util.locks import make_lock
import time
from collections import deque
from concurrent.futures import (FIRST_COMPLETED, ThreadPoolExecutor,
                                TimeoutError as _FutureTimeout, wait)
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..stats import health as _health
from ..util import tracing
from ..util import config
from ..util.profiling import StageTimer

DEFAULT_WINDOW = 4
GATHER_WINDOW_ENV = "SW_EC_GATHER_WINDOW"
HEDGE_MS_ENV = "SW_EC_HEDGE_MS"

_CONTENT_RANGE_RE = re.compile(r"bytes\s+(\d+)-(\d+)/(\d+)")


def auto_slab(shard_size: int, default: int = 8 << 20,
              min_slab: int = 1 << 20, target_stripes: int = 4) -> int:
    """Slab size for a rebuild when the caller didn't pick one. The
    default 8 MB slab is right for volume-scale shards, but a shard
    smaller than ~one slab degenerates to a single stripe — nothing for
    the gather to overlap with the decode. Shrink the slab (never below
    ``min_slab``) so the stream has at least ``target_stripes`` stripes;
    truly tiny shards keep the default (one stripe — pipelining dust
    costs more than it saves)."""
    if shard_size <= 2 * min_slab:
        return default
    per = -(-shard_size // target_stripes)
    return max(min_slab, min(default, per))


def gather_window() -> int:
    return max(1, config.env_int(GATHER_WINDOW_ENV))


def default_hedge_ms() -> float:
    return config.env_float(HEDGE_MS_ENV)


# hedged duplicates run here rather than in the gather pool: a stripe
# worker submitting back into its own (possibly saturated) pool could
# deadlock the window
_HEDGE_POOL: Optional[ThreadPoolExecutor] = None
_HEDGE_LOCK = make_lock("gather._HEDGE_LOCK")


def _hedge_pool() -> ThreadPoolExecutor:
    global _HEDGE_POOL
    with _HEDGE_LOCK:
        if _HEDGE_POOL is None:
            _HEDGE_POOL = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="ec-gather-hedge")
        return _HEDGE_POOL


class GatherStats:
    """Counters + busy-time accounting shared by every reader of one
    gather. Busy time is the UNION of fetch intervals (fetches overlap
    across stripes/rows), so ``bytes / busy_s`` is the effective gather
    bandwidth, comparable to what a serialized copy phase would need."""

    def __init__(self):
        self.timer = StageTimer()
        self._lock = make_lock("gather.GatherStats._lock")
        self.fetches = 0
        self.bytes = 0
        self.remote_bytes = 0
        self.hedges_fired = 0
        self.hedges_won = 0
        self.hedges_lost = 0
        self.retries = 0
        self.stripes = 0
        self.peak_buffered = 0
        self.remote_shards = 0
        self.local_shards = 0
        # per-holder accounting feeds the health scoreboard drill:
        # "routing on issues strictly fewer reads to the slow holder"
        # is only assertable if someone counts reads per holder
        self.holder_fetches: Dict[str, int] = {}
        self.holder_errors: Dict[str, int] = {}

    def add_fetch(self, nbytes: int, t0: float, t1: float,
                  remote: bool = False, holder: Optional[str] = None):
        self.timer.add("gather", t1 - t0, nbytes, interval=(t0, t1))
        with self._lock:
            self.fetches += 1
            self.bytes += nbytes
            if remote:
                self.remote_bytes += nbytes
            if holder:
                self.holder_fetches[holder] = \
                    self.holder_fetches.get(holder, 0) + 1

    def add_holder_error(self, holder: str):
        with self._lock:
            self.holder_errors[holder] = \
                self.holder_errors.get(holder, 0) + 1

    def add_hedge_fired(self):
        with self._lock:
            self.hedges_fired += 1

    def add_hedge_won(self):
        with self._lock:
            self.hedges_won += 1

    def add_hedge_lost(self):
        with self._lock:
            self.hedges_lost += 1

    def add_retry(self):
        with self._lock:
            self.retries += 1

    def busy_s(self) -> float:
        return self.timer.busy_time("gather")

    def mbps(self) -> float:
        busy = self.busy_s()
        if busy <= 0:
            return 0.0
        return self.bytes / busy / 1e6

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "gather_bytes": self.bytes,
                "gather_remote_bytes": self.remote_bytes,
                "gather_fetches": self.fetches,
                "hedges_fired": self.hedges_fired,
                "hedges_won": self.hedges_won,
                "hedges_lost": self.hedges_lost,
                "gather_retries": self.retries,
                "gather_stripes": self.stripes,
                "peak_gather_buffer": self.peak_buffered,
                "holder_fetches": dict(self.holder_fetches),
                "holder_errors": dict(self.holder_errors),
            }


class LocalShardReader:
    """Range reads of a survivor shard already on the rebuilder's disk.
    Opens per call — the gather pool reads several stripes of one shard
    concurrently, and a shared seek pointer would race."""

    remote = False

    def __init__(self, path: str, stats: Optional[GatherStats] = None):
        self.path = path
        self.stats = stats or GatherStats()

    def read(self, off: int, n: int, stripe_idx: int = 0) -> bytes:
        t0 = time.perf_counter()
        with open(self.path, "rb") as f:
            f.seek(off)
            data = f.read(n)
        if len(data) != n:
            raise IOError(f"short read of {self.path} at {off}: "
                          f"{len(data)} < {n}")
        self.stats.add_fetch(n, t0, time.perf_counter())
        return data


class RemoteShardReader:
    """Ranged reads of one survivor shard from its holder set, with
    round-robin striping, failover retries and optional hedging."""

    remote = True

    def __init__(self, vid: int, sid: int, holders: Sequence[str],
                 stats: Optional[GatherStats] = None,
                 timeout: float = 300.0,
                 hedge_ms: Optional[float] = None):
        if not holders:
            raise ValueError(f"shard {vid}.{sid}: no holders")
        self.vid = vid
        self.sid = sid
        self.holders = list(holders)
        self.stats = stats or GatherStats()
        self.span = None     # set by StripedGatherSource: trace parent
        self.timeout = timeout
        self.hedge_s = (default_hedge_ms() if hedge_ms is None
                        else float(hedge_ms)) / 1000.0

    # transport hooks — RemoteRepairReader overrides to hit the
    # projected-read route with a different method/response size while
    # inheriting rotation, failover and hedging unchanged
    _method = "GET"
    # health-scoreboard latency kind for fetches issued by this reader
    _health_kind = "shard_read"

    def _url(self, holder: str, off: int, n: int) -> str:
        return (f"http://{holder}/admin/ec/shard_read?volume={self.vid}"
                f"&shard={self.sid}&offset={off}&size={n}")

    def _expect_len(self, n: int) -> int:
        """Response bytes expected for an n-byte shard range."""
        return n

    def _read_one(self, holder: str, off: int, n: int) -> bytes:
        from ..server.http_util import HttpError, http_call
        # pool/hedge worker threads don't inherit the tracing
        # contextvar — carry the rebuild span's traceparent explicitly
        # so the holders' shard_read spans join the rebuild trace
        hdrs = None
        if self.span is not None:
            hdrs = {tracing.TRACEPARENT_HEADER: self.span.traceparent()}
        expect = self._expect_len(n)
        t0 = time.perf_counter()
        try:
            data = http_call(self._method, self._url(holder, off, n),
                             headers=hdrs, timeout=self.timeout)
            if len(data) != expect:
                raise HttpError(
                    502, f"short shard read {self.vid}.{self.sid} from "
                         f"{holder} at {off}: {len(data)} < {expect}")
        except Exception:
            self.stats.add_holder_error(holder)
            _health.BOARD.record_error(holder, self._health_kind)
            raise
        t1 = time.perf_counter()
        self.stats.add_fetch(len(data), t0, t1, remote=True,
                             holder=holder)
        _health.BOARD.record_latency(holder, self._health_kind, t1 - t0)
        return data

    def _read_failover(self, order: Sequence[str], off: int,
                       n: int) -> bytes:
        last = None
        for i, holder in enumerate(order):
            if i:
                self.stats.add_retry()
            try:
                return self._read_one(holder, off, n)
            except Exception as e:  # noqa: BLE001 - try the next holder
                last = e
        raise last

    def _attribute_hedge_loss(self, loser_future, loser: str,
                              winner: str):
        """The race is decided: whenever the losing duplicate finishes
        draining (maybe much later), charge the loss to the losing
        holder.  The loser's full latency is recorded by its own
        _read_one when the drained duplicate completes — the timing
        that used to be discarded — so the callback only needs to add
        the hedge-loss attribution."""
        self.stats.add_hedge_lost()

        def _done(_f):
            _health.BOARD.record_hedge_loss(loser, winner)

        loser_future.add_done_callback(_done)

    def read(self, off: int, n: int, stripe_idx: int = 0) -> bytes:
        h = self.holders
        # rotation both spreads load (consecutive stripes of a
        # replicated shard split across its holders) and fixes the
        # failover/hedge order for this stripe
        order = [h[(stripe_idx + j) % len(h)] for j in range(len(h))]
        if len(order) > 1 and _health.routing_enabled():
            # demote unhealthy holders to the back of the failover /
            # hedge order (stable within each class, so the rotation's
            # load-spreading survives among healthy peers)
            order = _health.BOARD.order_by_health(order)
        if self.hedge_s <= 0 or len(order) < 2:
            return self._read_failover(order, off, n)
        ex = _hedge_pool()
        primary = ex.submit(self._read_one, order[0], off, n)
        try:
            return primary.result(timeout=self.hedge_s)
        except _FutureTimeout:
            pass
        except Exception:  # noqa: BLE001 - fast failure: plain failover
            self.stats.add_retry()
            return self._read_failover(order[1:], off, n)
        # leading holder is past the hedge deadline: race a duplicate on
        # the next holder; first success wins, the loser drains its
        # response body in the pool thread and its socket goes back to
        # the connection pool
        self.stats.add_hedge_fired()
        secondary = ex.submit(self._read_one, order[1], off, n)
        pending = {primary, secondary}
        last = None
        while pending:
            done, pending = wait(pending, return_when=FIRST_COMPLETED)
            for f in done:
                err = f.exception()
                if err is None:
                    if f is secondary:
                        self.stats.add_hedge_won()
                        self._attribute_hedge_loss(
                            primary, order[0], order[1])
                    else:
                        self._attribute_hedge_loss(
                            secondary, order[1], order[0])
                    return f.result()
                last = err
        if len(order) > 2:
            self.stats.add_retry()
            return self._read_failover(order[2:], off, n)
        raise last


def probe_shard_size(vid: int, sid: int, holders: Sequence[str],
                     timeout: float = 30.0) -> int:
    """Total shard size via a one-byte suffix-range read: the 206's
    ``Content-Range: bytes a-b/total`` carries the full size without
    transferring the shard (and exercises the ``bytes=-N`` path).

    A holder that rejects the suffix form with 416 (strict servers do
    for some edge encodings) falls back to sizing the shard with
    1-byte ``offset=`` reads — double the offset until EOF, then
    binary-search the boundary: ~2*log2(size) tiny requests instead of
    transferring (or asking the holder to buffer) the whole shard."""
    from ..server.http_util import HttpError, http_call, \
        http_get_with_headers

    def _size_by_tiny_reads(url: str) -> int:
        def has_byte(off: int) -> bool:
            data = http_call("GET", url + f"&offset={off}&size=1",
                             timeout=timeout)
            return len(data) > 0

        if not has_byte(0):
            return 0
        lo, hi = 0, 1
        while has_byte(hi):
            lo, hi = hi, hi * 2
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if has_byte(mid):
                lo = mid
            else:
                hi = mid
        return lo + 1

    last = None
    for holder in holders:
        url = (f"http://{holder}/admin/ec/shard_read?volume={vid}"
               f"&shard={sid}")
        try:
            _, hdrs = http_get_with_headers(
                url, timeout=timeout, headers={"Range": "bytes=-1"})
        except HttpError as e:
            if e.status == 416:
                try:
                    return _size_by_tiny_reads(url)
                except HttpError as e2:
                    last = e2
                    continue
            last = e
            continue
        cr = next((v for k, v in hdrs.items()
                   if k.lower() == "content-range"), "")
        m = _CONTENT_RANGE_RE.match(cr or "")
        if m:
            return int(m.group(3))
        last = HttpError(
            502, f"no Content-Range from {holder} for {vid}.{sid}")
    if last is not None:
        raise last
    raise ValueError(f"shard {vid}.{sid}: no holders to probe")


class ShardSizeCache:
    """Per-rebuild memo of ``probe_shard_size`` keyed by (vid, sid).

    Trace repair sizes the lost shard off whichever survivor answers
    first, and a multi-volume rebuild touches the same survivors
    repeatedly — one suffix probe per shard per rebuild is enough.
    ``probes`` counts actual wire probes so tests can assert the memo
    held."""

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout
        self.probes = 0
        self._sizes: Dict[Tuple[int, int], int] = {}
        self._lock = make_lock("gather.ShardSizeCache._lock")

    def get(self, vid: int, sid: int, holders: Sequence[str]) -> int:
        key = (int(vid), int(sid))
        with self._lock:
            if key in self._sizes:
                return self._sizes[key]
        size = probe_shard_size(vid, sid, holders, timeout=self.timeout)
        with self._lock:
            self.probes += 1
            self._sizes[key] = size
        return size


class RemoteRepairReader(RemoteShardReader):
    """Projected reads for trace repair: asks the holder to apply this
    survivor's GF(2^8) trace masks server-side and ship only the packed
    symbol planes — ``len(masks) * ceil(n/8)`` bytes for an n-byte
    range. Rotation, failover and hedging come from the base class."""

    _method = "POST"
    _health_kind = "repair_read"

    def __init__(self, vid: int, sid: int, holders: Sequence[str],
                 masks: Sequence[int],
                 stats: Optional[GatherStats] = None,
                 timeout: float = 300.0,
                 hedge_ms: Optional[float] = None):
        super().__init__(vid, sid, holders, stats=stats, timeout=timeout,
                         hedge_ms=hedge_ms)
        if not masks:
            raise ValueError(f"shard {vid}.{sid}: no repair masks")
        self.masks = [int(x) for x in masks]

    def _url(self, holder: str, off: int, n: int) -> str:
        m = ",".join(str(x) for x in self.masks)
        return (f"http://{holder}/admin/ec/shard_repair_read"
                f"?volume={self.vid}&shard={self.sid}"
                f"&offset={off}&size={n}&masks={m}")

    def _expect_len(self, n: int) -> int:
        return len(self.masks) * ((n + 7) // 8)


class LocalRepairReader:
    """Trace projection of a survivor shard already on the rebuilder's
    disk: read the range locally, project, and account only the symbol
    bytes (the range itself never crossed the network)."""

    remote = False

    def __init__(self, path: str, masks: Sequence[int],
                 stats: Optional[GatherStats] = None):
        if not masks:
            raise ValueError(f"{path}: no repair masks")
        self.path = path
        self.masks = [int(x) for x in masks]
        self.stats = stats or GatherStats()

    def read(self, off: int, n: int, stripe_idx: int = 0) -> bytes:
        from ..ops.codec import project_slab
        t0 = time.perf_counter()
        with open(self.path, "rb") as f:
            f.seek(off)
            data = f.read(n)
        if len(data) != n:
            raise IOError(f"short read of {self.path} at {off}: "
                          f"{len(data)} < {n}")
        planes = project_slab(np.frombuffer(data, dtype=np.uint8),
                              self.masks)
        self.stats.add_fetch(planes.nbytes, t0, time.perf_counter())
        return planes.tobytes()


def fetch_index_files(base_name: str, holders: Sequence[str],
                      timeout: float = 300.0) -> List[str]:
    """Pull the small index sidecars onto the rebuilder: .ecx required
    (the rebuilt .ecx tombstone replay and the mount need it), .vif and
    .ecj best-effort. These are KB-sized — the only whole files the
    streaming rebuild copies."""
    from ..server.http_util import HttpError, http_call
    name = os.path.basename(base_name)
    fetched: List[str] = []
    for ext, required in ((".ecx", True), (".vif", False), (".ecj", False)):
        if os.path.exists(base_name + ext):
            continue
        last = None
        data = None
        for holder in holders:
            try:
                data = http_call(
                    "GET",
                    f"http://{holder}/admin/file?name={name}{ext}",
                    timeout=timeout)
                break
            except HttpError as e:
                last = e
                data = None
        if data is None:
            if required:
                raise last if last is not None else HttpError(
                    404, f"{name}{ext}: no holder serves it")
            continue
        with open(base_name + ext, "wb") as f:
            f.write(data)
        fetched.append(ext)
    return fetched


class StripedGatherSource:
    """The survivor stream: ``slabs()`` yields ``(meta, (k, w) uint8)``
    stripes in order, fetching up to ``window`` stripes ahead across a
    shared thread pool. ``readers`` are the first-k survivors in decode
    plan order — local files and remote holders mixed freely."""

    def __init__(self, readers: Sequence, shard_size: int,
                 slab: int = 8 << 20, window: Optional[int] = None,
                 stats: Optional[GatherStats] = None,
                 parent_span=None):
        if not readers:
            raise ValueError("no survivor readers")
        self.readers = list(readers)
        self.shard_size = int(shard_size)
        self.slab = max(1, int(slab))
        self.window = max(1, int(window) if window else gather_window())
        self.stats = stats or GatherStats()
        self.parent_span = parent_span
        for r in self.readers:
            r.stats = self.stats
            r.span = parent_span
        self.stats.remote_shards = sum(
            1 for r in self.readers if getattr(r, "remote", False))
        self.stats.local_shards = len(self.readers) - \
            self.stats.remote_shards
        self._buffered = 0
        self._lock = make_lock("gather.GatherSource._lock")

    def _note_buffered(self, delta: int):
        with self._lock:
            self._buffered += delta
            if self._buffered > self.stats.peak_buffered:
                self.stats.peak_buffered = self._buffered

    # stream-shape hooks — RepairGatherSource reshapes both without
    # touching the window/pool/ordering machinery
    def _stripe_nbytes(self, w: int) -> int:
        """Buffered bytes one in-flight stripe accounts for."""
        return len(self.readers) * w

    def _assemble(self, bufs: List[bytes], w: int) -> np.ndarray:
        """Row buffers of one stripe -> the block the consumer wants."""
        rows = [np.frombuffer(b, dtype=np.uint8) for b in bufs]
        return np.stack(rows, axis=0)

    def slabs(self):
        k = len(self.readers)
        stripes: List[Tuple[int, int]] = [
            (off, min(self.slab, self.shard_size - off))
            for off in range(0, self.shard_size, self.slab)]
        self.stats.stripes = len(stripes)
        if not stripes:
            return
        workers = min(16, max(2, min(self.window, len(stripes)) * k))
        pool = ThreadPoolExecutor(max_workers=workers,
                                  thread_name_prefix="ec-gather")
        pending: deque = deque()

        def submit(idx: int):
            off, w = stripes[idx]
            # account BEFORE the fetches start: in-flight rows are
            # buffered memory too, and the bound must hold even when
            # every submitted row completes before the consumer drains
            self._note_buffered(self._stripe_nbytes(w))
            t_sub = time.perf_counter()
            futs = [pool.submit(self.readers[r].read, off, w, idx)
                    for r in range(k)]
            pending.append((idx, off, w, t_sub, futs))

        try:
            nxt = 0
            while nxt < len(stripes) and len(pending) < self.window:
                submit(nxt)
                nxt += 1
            while pending:
                idx, off, w, t_sub, futs = pending.popleft()
                data = self._assemble([f.result() for f in futs], w)
                tracing.record_span(
                    "gather.stripe", time.perf_counter() - t_sub,
                    parent=self.parent_span, op="ec.rebuild.gather",
                    stripe=idx, offset=off,
                    bytes=self._stripe_nbytes(w))
                self._note_buffered(-self._stripe_nbytes(w))
                if nxt < len(stripes):
                    submit(nxt)
                    nxt += 1
                yield (idx, off, w), data
        finally:
            pool.shutdown(wait=False, cancel_futures=True)


class RepairGatherSource(StripedGatherSource):
    """Trace-repair symbol stream: the readers are one projection
    reader per plan helper (``ops/codec.RepairPlan`` order), each
    returning its packed symbol planes for the stripe range. ``slabs()``
    yields ``(meta, (total_bits, ceil(w/8)) uint8)`` blocks — the
    concatenated planes in helper-then-mask order, ready for the fused
    combine matmul. The bounded window, round-robin rotation, failover
    and hedging all come from the base source; only the stripe shape
    and memory accounting differ."""

    def __init__(self, readers: Sequence, shard_size: int, plan,
                 slab: int = 8 << 20, window: Optional[int] = None,
                 stats: Optional[GatherStats] = None,
                 parent_span=None):
        if len(readers) != len(plan.helpers):
            raise ValueError(
                f"need one reader per helper: {len(readers)} != "
                f"{len(plan.helpers)}")
        super().__init__(readers, shard_size, slab=slab, window=window,
                         stats=stats, parent_span=parent_span)
        self.plan = plan

    def _stripe_nbytes(self, w: int) -> int:
        return self.plan.total_bits * ((w + 7) // 8)

    def _assemble(self, bufs: List[bytes], w: int) -> np.ndarray:
        stride = (w + 7) // 8
        rows = [np.frombuffer(b, dtype=np.uint8).reshape(-1, stride)
                for b in bufs]
        return np.concatenate(rows, axis=0)
