"""One windowed stripe-transport layer for every EC data mover.

``ec/gather.py`` (rebuild/repair pull) and ``ec/spread.py`` (encode
push) each grew a private copy of the same transport: a bounded
in-flight window with peak-buffer accounting, per-holder rotation +
failover, ``SW_EC_HEDGE_MS`` hedging with loser-drain health
attribution, contiguous-run merging and local fast paths. This module
is that transport, once — a *pull* side (``StripedPull``: stripe
readers fan out over a pool, stripes yield strictly in order) and a
*push* side (``StripedPush``: per-target workers drain bounded send
queues, merging contiguous runs). Gather, spread, scrub and the tier
demotion pipeline are thin clients; hedging and health routing are
therefore available on the write path too, not just the read path.

Shape of the stream on both sides: a *stripe* is one slab-aligned byte
range ``[off, off+w)`` of every shard. The pull side materializes it as
a ``(k, w)`` uint8 block for the decode; the push side receives it as
``(k, w)`` data + ``(m, w)`` parity rows from the encode. In-flight
memory is O(window * shards * slab) on either side, never O(volume).

Straggler defenses (shared):
  * rotation: stripe ``s`` leads with holder ``s % len(holders)`` so
    consecutive stripes split across replicas instead of hammering one.
  * failover: a failed pull retries the remaining holders in rotation
    order; a push target that dies before acking any byte hands its
    shard set to a spare and replays from offset 0.
  * hedging (``SW_EC_HEDGE_MS``, default off): a pull past the deadline
    races a duplicate on the next holder; a first push run past the
    deadline races a duplicate stage on a spare target. The loser is
    never cancelled — its response drains in the hedge pool so the
    socket parks back in the keep-alive pool — and the loss is charged
    to the slow holder on the health scoreboard.
  * health routing (``SW_EC_HEALTH_ROUTING``): unhealthy holders sort
    to the back of the pull failover order; the healthiest spare is
    picked first on push failover.
"""

from __future__ import annotations

import os
import queue
import re
import threading
import time
from collections import deque
from concurrent.futures import (FIRST_COMPLETED, ThreadPoolExecutor,
                                TimeoutError as _FutureTimeout, wait)
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..stats import health as _health
from ..util import config, tracing
from ..util.locks import make_lock
from ..util.profiling import StageTimer

DEFAULT_WINDOW = 4
PULL_WINDOW_ENV = "SW_EC_GATHER_WINDOW"
PUSH_WINDOW_ENV = "SW_EC_SPREAD_WINDOW"
HEDGE_MS_ENV = "SW_EC_HEDGE_MS"

_STAGED_RE = re.compile(r"staged=(\d+)")

_SENTINEL = object()


def pull_window() -> int:
    return max(1, config.env_int(PULL_WINDOW_ENV))


def push_window() -> int:
    return max(1, config.env_int(PUSH_WINDOW_ENV))


def default_hedge_ms() -> float:
    return config.env_float(HEDGE_MS_ENV)


# hedged duplicates run here rather than in the mover's own pool: a
# stripe worker submitting back into its (possibly saturated) pool
# could deadlock the window
_HEDGE_POOL: Optional[ThreadPoolExecutor] = None
_HEDGE_LOCK = make_lock("transport._HEDGE_LOCK")


def hedge_pool() -> ThreadPoolExecutor:
    global _HEDGE_POOL
    with _HEDGE_LOCK:
        if _HEDGE_POOL is None:
            _HEDGE_POOL = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="ec-transport-hedge")
        return _HEDGE_POOL


class SpreadError(Exception):
    """A transport operation failed beyond what retry/failover can
    absorb. (Historic name — the push side raised it first; the shared
    layer kept it so existing handlers don't churn.)"""


class TransportStats:
    """Counters + busy-time accounting shared by every endpoint of one
    transport run. Busy time is the UNION of transfer intervals
    (transfers overlap across stripes/rows/targets), so
    ``bytes / busy_s`` is the effective bandwidth, comparable to what a
    serialized copy phase would need. ``stage`` names the role
    ("gather"/"spread"/...) and prefixes the snapshot keys, so one
    class serves both metric families plus the merged ``ec_transport_*``
    export."""

    stage = "transport"

    def __init__(self):
        self.timer = StageTimer()
        self._lock = make_lock("transport.TransportStats._lock")
        self.fetches = 0
        self.sends = 0
        self.bytes = 0
        self.remote_bytes = 0
        self.hedges_fired = 0
        self.hedges_won = 0
        self.hedges_lost = 0
        self.retries = 0
        self.failovers = 0
        self.stripes = 0
        self.peak_buffered = 0
        self.remote_shards = 0
        self.local_shards = 0
        # per-holder accounting feeds the health scoreboard drill:
        # "routing on issues strictly fewer reads to the slow holder"
        # is only assertable if someone counts transfers per holder
        self.holder_fetches: Dict[str, int] = {}
        self.holder_errors: Dict[str, int] = {}

    def add_fetch(self, nbytes: int, t0: float, t1: float,
                  remote: bool = False, holder: Optional[str] = None):
        self.timer.add(self.stage, t1 - t0, nbytes, interval=(t0, t1))
        with self._lock:
            self.fetches += 1
            self.bytes += nbytes
            if remote:
                self.remote_bytes += nbytes
            if holder:
                self.holder_fetches[holder] = \
                    self.holder_fetches.get(holder, 0) + 1

    def add_send(self, nbytes: int, t0: float, t1: float,
                 holder: Optional[str] = None):
        self.timer.add(self.stage, t1 - t0, nbytes, interval=(t0, t1))
        with self._lock:
            self.sends += 1
            self.bytes += nbytes
            if holder:
                self.holder_fetches[holder] = \
                    self.holder_fetches.get(holder, 0) + 1

    def add_holder_error(self, holder: str):
        with self._lock:
            self.holder_errors[holder] = \
                self.holder_errors.get(holder, 0) + 1

    def add_hedge_fired(self):
        with self._lock:
            self.hedges_fired += 1

    def add_hedge_won(self):
        with self._lock:
            self.hedges_won += 1

    def add_hedge_lost(self):
        with self._lock:
            self.hedges_lost += 1

    def add_retry(self):
        with self._lock:
            self.retries += 1

    def add_failover(self):
        with self._lock:
            self.failovers += 1

    def busy_s(self) -> float:
        return self.timer.busy_time(self.stage)

    def mbps(self) -> float:
        busy = self.busy_s()
        if busy <= 0:
            return 0.0
        return self.bytes / busy / 1e6

    def snapshot(self) -> Dict[str, float]:
        s = self.stage
        with self._lock:
            return {
                f"{s}_bytes": self.bytes,
                f"{s}_remote_bytes": self.remote_bytes,
                f"{s}_fetches": self.fetches,
                f"{s}_sends": self.sends,
                f"{s}_stripes": self.stripes,
                f"{s}_retries": self.retries,
                f"{s}_failovers": self.failovers,
                f"peak_{s}_buffer": self.peak_buffered,
                "hedges_fired": self.hedges_fired,
                "hedges_won": self.hedges_won,
                "hedges_lost": self.hedges_lost,
                "holder_fetches": dict(self.holder_fetches),
                "holder_errors": dict(self.holder_errors),
            }


class GatherStats(TransportStats):
    """Pull-side role of the shared stats: snapshot keys are
    ``gather_*`` (what ``observe_gather`` and the rebuild/repair stats
    dicts have always carried)."""

    stage = "gather"


class SpreadStats(TransportStats):
    """Push-side role of the shared stats: snapshot keys are
    ``spread_*`` (what ``observe_spread`` and the encode stats dicts
    have always carried)."""

    stage = "spread"


# ---------------------------------------------------------------------------
# pull side: stripe readers


class LocalShardReader:
    """Range reads of a shard already on this node's disk. Opens per
    call — the pull pool reads several stripes of one shard
    concurrently, and a shared seek pointer would race."""

    remote = False

    def __init__(self, path: str, stats: Optional[TransportStats] = None):
        self.path = path
        self.stats = stats or GatherStats()

    def read(self, off: int, n: int, stripe_idx: int = 0) -> bytes:
        t0 = time.perf_counter()
        with open(self.path, "rb") as f:
            f.seek(off)
            data = f.read(n)
        if len(data) != n:
            raise IOError(f"short read of {self.path} at {off}: "
                          f"{len(data)} < {n}")
        self.stats.add_fetch(n, t0, time.perf_counter())
        return data


class RemoteShardReader:
    """Ranged reads of one shard from its holder set, with round-robin
    striping, failover retries and optional hedging."""

    remote = True

    def __init__(self, vid: int, sid: int, holders: Sequence[str],
                 stats: Optional[TransportStats] = None,
                 timeout: float = 300.0,
                 hedge_ms: Optional[float] = None):
        if not holders:
            raise ValueError(f"shard {vid}.{sid}: no holders")
        self.vid = vid
        self.sid = sid
        self.holders = list(holders)
        self.stats = stats or GatherStats()
        self.span = None     # set by StripedPull: trace parent
        self.timeout = timeout
        self.hedge_s = (default_hedge_ms() if hedge_ms is None
                        else float(hedge_ms)) / 1000.0

    # transport hooks — RemoteRepairReader overrides to hit the
    # projected-read route with a different method/response size while
    # inheriting rotation, failover and hedging unchanged
    _method = "GET"
    # health-scoreboard latency kind for fetches issued by this reader
    _health_kind = "shard_read"

    def _url(self, holder: str, off: int, n: int) -> str:
        return (f"http://{holder}/admin/ec/shard_read?volume={self.vid}"
                f"&shard={self.sid}&offset={off}&size={n}")

    def _expect_len(self, n: int) -> int:
        """Response bytes expected for an n-byte shard range."""
        return n

    def _read_one(self, holder: str, off: int, n: int) -> bytes:
        from ..server.http_util import HttpError, http_call
        # pool/hedge worker threads don't inherit the tracing
        # contextvar — carry the caller span's traceparent explicitly
        # so the holders' shard_read spans join the caller's trace
        hdrs = None
        if self.span is not None:
            hdrs = {tracing.TRACEPARENT_HEADER: self.span.traceparent()}
        expect = self._expect_len(n)
        t0 = time.perf_counter()
        try:
            data = http_call(self._method, self._url(holder, off, n),
                             headers=hdrs, timeout=self.timeout)
            if len(data) != expect:
                raise HttpError(
                    502, f"short shard read {self.vid}.{self.sid} from "
                         f"{holder} at {off}: {len(data)} < {expect}")
        except Exception:
            self.stats.add_holder_error(holder)
            _health.BOARD.record_error(holder, self._health_kind)
            raise
        t1 = time.perf_counter()
        self.stats.add_fetch(len(data), t0, t1, remote=True,
                             holder=holder)
        _health.BOARD.record_latency(holder, self._health_kind, t1 - t0)
        return data

    def _read_failover(self, order: Sequence[str], off: int,
                       n: int) -> bytes:
        last = None
        for i, holder in enumerate(order):
            if i:
                self.stats.add_retry()
            try:
                return self._read_one(holder, off, n)
            except Exception as e:  # noqa: BLE001 - try the next holder
                last = e
        raise last

    def _attribute_hedge_loss(self, loser_future, loser: str,
                              winner: str):
        """The race is decided: whenever the losing duplicate finishes
        draining (maybe much later), charge the loss to the losing
        holder.  The loser's full latency is recorded by its own
        _read_one when the drained duplicate completes — the timing
        that used to be discarded — so the callback only needs to add
        the hedge-loss attribution."""
        self.stats.add_hedge_lost()

        def _done(_f):
            _health.BOARD.record_hedge_loss(loser, winner)

        loser_future.add_done_callback(_done)

    def read(self, off: int, n: int, stripe_idx: int = 0) -> bytes:
        h = self.holders
        # rotation both spreads load (consecutive stripes of a
        # replicated shard split across its holders) and fixes the
        # failover/hedge order for this stripe
        order = [h[(stripe_idx + j) % len(h)] for j in range(len(h))]
        if len(order) > 1 and _health.routing_enabled():
            # demote unhealthy holders to the back of the failover /
            # hedge order (stable within each class, so the rotation's
            # load-spreading survives among healthy peers)
            order = _health.BOARD.order_by_health(order)
        if self.hedge_s <= 0 or len(order) < 2:
            return self._read_failover(order, off, n)
        ex = hedge_pool()
        primary = ex.submit(self._read_one, order[0], off, n)
        try:
            return primary.result(timeout=self.hedge_s)
        except _FutureTimeout:
            pass
        except Exception:  # noqa: BLE001 - fast failure: plain failover
            self.stats.add_retry()
            return self._read_failover(order[1:], off, n)
        # leading holder is past the hedge deadline: race a duplicate on
        # the next holder; first success wins, the loser drains its
        # response body in the pool thread and its socket goes back to
        # the connection pool
        self.stats.add_hedge_fired()
        secondary = ex.submit(self._read_one, order[1], off, n)
        pending = {primary, secondary}
        last = None
        while pending:
            done, pending = wait(pending, return_when=FIRST_COMPLETED)
            for f in done:
                err = f.exception()
                if err is None:
                    if f is secondary:
                        self.stats.add_hedge_won()
                        self._attribute_hedge_loss(
                            primary, order[0], order[1])
                    else:
                        self._attribute_hedge_loss(
                            secondary, order[1], order[0])
                    return f.result()
                last = err
        if len(order) > 2:
            self.stats.add_retry()
            return self._read_failover(order[2:], off, n)
        raise last


class StripedPull:
    """The pull pump: ``slabs()`` yields ``(meta, block)`` stripes in
    strict order, fetching up to ``window`` stripes ahead across a
    shared thread pool. ``readers`` are per-row endpoints (local files
    and remote holders mixed freely). Subclasses reshape the stream via
    the ``_stripe_nbytes``/``_assemble`` hooks without touching the
    window/pool/ordering machinery."""

    span_name = "gather.stripe"
    span_op = "ec.rebuild.gather"

    def __init__(self, readers: Sequence, shard_size: int,
                 slab: int = 8 << 20, window: Optional[int] = None,
                 stats: Optional[TransportStats] = None,
                 parent_span=None):
        if not readers:
            raise ValueError("no survivor readers")
        self.readers = list(readers)
        self.shard_size = int(shard_size)
        self.slab = max(1, int(slab))
        self.window = max(1, int(window) if window else pull_window())
        self.stats = stats if stats is not None else GatherStats()
        self.parent_span = parent_span
        for r in self.readers:
            r.stats = self.stats
            r.span = parent_span
        self.stats.remote_shards = sum(
            1 for r in self.readers if getattr(r, "remote", False))
        self.stats.local_shards = len(self.readers) - \
            self.stats.remote_shards
        self._buffered = 0
        self._lock = make_lock("transport.StripedPull._lock")

    def _note_buffered(self, delta: int):
        with self._lock:
            self._buffered += delta
            if self._buffered > self.stats.peak_buffered:
                self.stats.peak_buffered = self._buffered

    # stream-shape hooks
    def _stripe_nbytes(self, w: int) -> int:
        """Buffered bytes one in-flight stripe accounts for."""
        return len(self.readers) * w

    def _assemble(self, bufs: List[bytes], w: int) -> np.ndarray:
        """Row buffers of one stripe -> the block the consumer wants."""
        rows = [np.frombuffer(b, dtype=np.uint8) for b in bufs]
        return np.stack(rows, axis=0)

    def slabs(self):
        k = len(self.readers)
        stripes: List[Tuple[int, int]] = [
            (off, min(self.slab, self.shard_size - off))
            for off in range(0, self.shard_size, self.slab)]
        self.stats.stripes = len(stripes)
        if not stripes:
            return
        workers = min(16, max(2, min(self.window, len(stripes)) * k))
        pool = ThreadPoolExecutor(max_workers=workers,
                                  thread_name_prefix="ec-pull")
        pending: deque = deque()

        def submit(idx: int):
            off, w = stripes[idx]
            # account BEFORE the fetches start: in-flight rows are
            # buffered memory too, and the bound must hold even when
            # every submitted row completes before the consumer drains
            self._note_buffered(self._stripe_nbytes(w))
            t_sub = time.perf_counter()
            futs = [pool.submit(self.readers[r].read, off, w, idx)
                    for r in range(k)]
            pending.append((idx, off, w, t_sub, futs))

        try:
            nxt = 0
            while nxt < len(stripes) and len(pending) < self.window:
                submit(nxt)
                nxt += 1
            while pending:
                idx, off, w, t_sub, futs = pending.popleft()
                data = self._assemble([f.result() for f in futs], w)
                tracing.record_span(
                    self.span_name, time.perf_counter() - t_sub,
                    parent=self.parent_span, op=self.span_op,
                    stripe=idx, offset=off,
                    bytes=self._stripe_nbytes(w))
                self._note_buffered(-self._stripe_nbytes(w))
                if nxt < len(stripes):
                    submit(nxt)
                    nxt += 1
                yield (idx, off, w), data
        finally:
            pool.shutdown(wait=False, cancel_futures=True)


# ---------------------------------------------------------------------------
# push side: stripe writers


class LocalShardWriter:
    """Fast path for shards this node keeps: append to the local
    ``.part`` stage file, atomic-rename on finalize — the same
    no-partial-shards contract the remote protocol gives."""

    remote = False

    def __init__(self, path: str,
                 stats: Optional[TransportStats] = None):
        self.path = path
        self.part = path + ".part"
        self.stats = stats or SpreadStats()
        self.span = None
        self._f = None

    def send(self, url: Optional[str], off: int,
             chunks: Sequence[bytes]) -> int:
        t0 = time.perf_counter()
        if self._f is None:
            self._f = open(self.part, "wb" if off == 0 else "ab")
        if self._f.tell() != off:
            raise SpreadError(
                f"local shard write offset mismatch for {self.path}: "
                f"staged={self._f.tell()} offset={off}")
        n = 0
        for c in chunks:
            self._f.write(c)
            n += len(c)
        self.stats.add_send(n, t0, time.perf_counter())
        return n

    def finalize(self, url: Optional[str], size: int):
        if self._f is not None:
            self._f.close()
            self._f = None
        staged = os.path.getsize(self.part) if os.path.exists(self.part) \
            else -1
        if staged != size:
            raise SpreadError(
                f"local shard {self.path}: staged {staged} != {size}")
        os.replace(self.part, self.path)

    def abort(self, url: Optional[str]):
        if self._f is not None:
            self._f.close()
            self._f = None
        for p in (self.part,):
            try:
                os.remove(p)
            except OSError:
                pass


class RemoteShardWriter:
    """Pushes one shard's slab ranges to its holder: each run of
    contiguous chunks goes out as ONE chunked POST to
    ``/admin/ec/shard_write`` (append-at-expected-offset, 409 on
    mismatch), carrying the caller span's traceparent so the holder's
    spans join the trace. Every send feeds the health scoreboard under
    the ``shard_write`` kind — the push path sees slow holders with the
    same eyes the pull path does."""

    remote = True
    _health_kind = "shard_write"

    def __init__(self, vid: int, sid: int, collection: str = "",
                 stats: Optional[TransportStats] = None,
                 timeout: float = 300.0):
        self.vid = vid
        self.sid = sid
        self.collection = collection
        self.stats = stats or SpreadStats()
        self.span = None     # set by StripedPush: trace parent
        self.timeout = timeout

    def _url(self, holder: str, query: str) -> str:
        return (f"http://{holder}/admin/ec/shard_write?volume={self.vid}"
                f"&collection={self.collection}&shard={self.sid}&{query}")

    def _headers(self) -> Optional[dict]:
        # target worker threads don't inherit the tracing contextvar —
        # carry the caller span's traceparent explicitly
        if self.span is None:
            return None
        return {tracing.TRACEPARENT_HEADER: self.span.traceparent()}

    def send(self, url: str, off: int, chunks: Sequence[bytes]) -> int:
        from ..server.http_util import HttpError, post_chunked
        n = sum(len(c) for c in chunks)
        t0 = time.perf_counter()
        try:
            post_chunked(self._url(url, f"offset={off}"), chunks,
                         headers=self._headers(), timeout=self.timeout)
        except HttpError as e:
            if e.status == 409:
                # the holder's staged size disagrees; if it already
                # covers this run the previous delivery merely lost its
                # ack — don't re-append, don't fail
                m = _STAGED_RE.search(str(e))
                if m and int(m.group(1)) == off + n:
                    self.stats.add_send(n, t0, time.perf_counter(),
                                        holder=url)
                    return n
            self.stats.add_holder_error(url)
            _health.BOARD.record_error(url, self._health_kind)
            raise
        except Exception:
            self.stats.add_holder_error(url)
            _health.BOARD.record_error(url, self._health_kind)
            raise
        t1 = time.perf_counter()
        self.stats.add_send(n, t0, t1, holder=url)
        _health.BOARD.record_latency(url, self._health_kind, t1 - t0)
        return n

    def finalize(self, url: str, size: int):
        from ..server.http_util import http_call
        http_call("POST",
                  self._url(url, f"action=finalize&size={size}"),
                  headers=self._headers(), timeout=self.timeout)

    def abort(self, url: str):
        from ..server.http_util import http_call
        try:
            http_call("POST", self._url(url, "action=abort"),
                      headers=self._headers(), timeout=30.0)
        except Exception:  # noqa: BLE001 - best-effort cleanup
            pass


class TargetWorker(threading.Thread):
    """Drains one target's bounded send queue: pops queued
    ``(sid, off, chunk)`` items, merges per-shard contiguous runs, and
    sends each run as one chunked POST. Owns the target url so
    failover (re-assigning every shard of a dead target to a spare)
    is a single-variable swap. The FIRST run to a remote target may be
    hedged: past the ``SW_EC_HEDGE_MS`` deadline the same run races a
    duplicate stage on a spare, the first ack wins the shard set, and
    the loser's stage is aborted once its send drains."""

    def __init__(self, sink: "StripedPush", url: Optional[str],
                 sids: List[int], window: int):
        name = url or "local"
        super().__init__(daemon=True, name=f"ec-push-{name}")
        self.sink = sink
        self.url = url
        self.sids = list(sids)
        self.max_batch = max(1, window * len(sids))
        self.q: queue.Queue = queue.Queue(maxsize=self.max_batch)
        self.acked = 0
        self.error: Optional[BaseException] = None

    def run(self):
        try:
            stop = False
            while not stop:
                try:
                    item = self.q.get(timeout=0.1)
                except queue.Empty:
                    if self.sink.failed is not None:
                        return
                    continue
                batch = []
                while True:
                    if item is _SENTINEL:
                        stop = True
                        break
                    batch.append(item)
                    if len(batch) >= self.max_batch:
                        break
                    try:
                        item = self.q.get_nowait()
                    except queue.Empty:
                        break
                for sid, off, chunks in merge_runs(batch):
                    n = self._send_run(sid, off, chunks)
                    self.sink._note_buffered(-n)
        except BaseException as e:  # noqa: BLE001 - surfaced to consumer
            self.error = e
            self.sink._fail(e)

    def _send_run(self, sid: int, off: int, chunks) -> int:
        writer = self.sink.writers[sid]
        n = sum(len(c) for c in chunks)
        if (self.sink.hedge_s > 0 and self.url is not None
                and self.acked == 0 and off == 0):
            if self._send_run_hedged(writer, off, chunks, n):
                self._trace_run(sid, off, n)
                return n
        while True:
            last = None
            for attempt in range(2):
                if attempt:
                    self.sink.stats.add_retry()
                try:
                    writer.send(self.url, off, chunks)
                    self.acked += n
                    self._trace_run(sid, off, n)
                    return n
                except BaseException as e:  # noqa: BLE001 - retry/failover
                    last = e
            if self.acked > 0 or off != 0 or self.url is None:
                # bytes already landed on this target (or it's the local
                # disk): the dead holder's prefix is unreplayable — the
                # stripe stream never kept it. Abort; the caller falls
                # back to the copy flow.
                raise last
            spare = self.sink._take_spare(self.url)
            if spare is None:
                raise last
            dead, self.url = self.url, spare
            self.sink.stats.add_failover()
            writer.abort(dead)

    def _send_run_hedged(self, writer, off: int, chunks,
                         n: int) -> bool:
        """Hedge the first run of this target: if the leading holder
        has not acked within the deadline, race the same run against a
        spare's stage. Returns True when the run landed (possibly after
        swapping ``self.url`` to the winning spare); False hands the
        run to the plain retry/failover path — a duplicate re-send is
        safe because the holder's 409 ``staged=`` reply identifies a
        delivered-but-unacked run."""
        ex = hedge_pool()
        primary = ex.submit(writer.send, self.url, off, chunks)
        try:
            primary.result(timeout=self.sink.hedge_s)
            self.acked += n
            return True
        except _FutureTimeout:
            pass
        except Exception:  # noqa: BLE001 - fast failure: plain failover
            return False
        spare = self.sink._take_spare(self.url)
        if spare is None:
            # no rival to race: wait the slow send out
            try:
                primary.result()
            except Exception:  # noqa: BLE001 - plain path owns retries
                return False
            self.acked += n
            return True
        self.sink.stats.add_hedge_fired()
        secondary = ex.submit(writer.send, spare, off, chunks)
        pending = {primary, secondary}
        while pending:
            done, pending = wait(pending, return_when=FIRST_COMPLETED)
            for f in done:
                if f.exception() is not None:
                    continue
                self.sink.stats.add_hedge_lost()
                if f is secondary:
                    # the spare won: it owns this worker's shard set
                    # from here on; the slow holder's stage is aborted
                    # once its duplicate drains (the send is idempotent
                    # there — nothing else references the stage)
                    slow, self.url = self.url, spare
                    self.sink.stats.add_hedge_won()
                    self.sink.stats.add_failover()
                    _health.BOARD.record_hedge_loss(slow, spare)
                    primary.add_done_callback(
                        lambda _f, dead=slow: writer.abort(dead))
                else:
                    _health.BOARD.record_hedge_loss(spare, self.url)

                    def _cleanup(_f, spare=spare):
                        writer.abort(spare)
                        self.sink._return_spare(spare)

                    secondary.add_done_callback(_cleanup)
                self.acked += n
                return True
        # both failed: the plain path retries and fails over; give the
        # consumed spare back first so failover can still reach it
        self.sink._return_spare(spare)
        return False

    def _trace_run(self, sid: int, off: int, n: int):
        tracing.record_span(
            self.sink.span_name, 0.0, parent=self.sink.parent_span,
            op=self.sink.span_op, shard=sid, offset=off,
            bytes=n, target=self.url or "local")


def merge_runs(batch):
    """Merge a drained batch into per-shard contiguous runs, preserving
    per-shard order (queue order is stripe order, so each shard's
    offsets arrive ascending and contiguous)."""
    runs = []          # [sid, start_off, [chunks], next_off]
    open_run: Dict[int, list] = {}
    for sid, off, chunk in batch:
        run = open_run.get(sid)
        if run is not None and run[3] == off:
            run[2].append(chunk)
            run[3] += len(chunk)
        else:
            run = [sid, off, [chunk], off + len(chunk)]
            runs.append(run)
            open_run[sid] = run
    return [(sid, off, chunks) for sid, off, chunks, _ in runs]


class StripedPush:
    """The push pump: ``write_stripe`` routes each shard row of the
    arriving stripe to its holder's bounded send queue; per-target
    workers push the ranges while the producer makes the next stripes.
    Subclasses build the ``writers`` list (one endpoint per shard) and
    the ``by_target`` grouping; everything else — window accounting,
    blocked-time, failover spares, hedging, finalize/abort discipline,
    optional MB/s pacing — lives here."""

    span_name = "spread.run"
    span_op = "ec.encode.spread"

    def __init__(self, writers: List, by_target: Dict[Optional[str],
                                                      List[int]],
                 spares: Optional[Sequence[str]] = None,
                 window: Optional[int] = None,
                 stats: Optional[TransportStats] = None,
                 parent_span=None, hedge_ms: Optional[float] = None,
                 rate_mbps: float = 0.0):
        self.total = len(writers)
        self.window = max(1, int(window) if window else push_window())
        self.stats = stats if stats is not None else SpreadStats()
        self.parent_span = parent_span
        self.hedge_s = (default_hedge_ms() if hedge_ms is None
                        else float(hedge_ms)) / 1000.0
        # producer-side MB/s ceiling (tier demotions under live
        # traffic): same discipline as the scrub's pacing — sleep the
        # producer so cumulative pushed bytes stay under the cap
        self.rate_mbps = float(rate_mbps or 0.0)
        self._rate_t0 = None
        self._rate_bytes = 0
        self.offset = 0
        self.failed: Optional[BaseException] = None
        self._spares = [s for s in (spares or []) if s]
        self._lock = make_lock("transport.StripedPush._lock")
        self._buffered = 0
        self.writers = list(writers)
        for w in self.writers:
            w.stats = self.stats
            w.span = parent_span
        self.stats.remote_shards = sum(
            1 for w in self.writers if w.remote)
        self.stats.local_shards = self.total - self.stats.remote_shards
        self.workers = [
            TargetWorker(self, url, sids, self.window)
            for url, sids in by_target.items()]
        self._worker_of = {}
        for w in self.workers:
            for sid in w.sids:
                self._worker_of[sid] = w
        self.blocked_s = 0.0     # producer time lost to full windows
        for w in self.workers:
            w.start()

    # -- shared bookkeeping -------------------------------------------------
    def _note_buffered(self, delta: int):
        with self._lock:
            self._buffered += delta
            if self._buffered > self.stats.peak_buffered:
                self.stats.peak_buffered = self._buffered

    def _fail(self, e: BaseException):
        with self._lock:
            if self.failed is None:
                self.failed = e

    def _take_spare(self, dead: Optional[str]) -> Optional[str]:
        with self._lock:
            cands = self._spares
            if len(cands) > 1 and _health.routing_enabled():
                # healthiest spare first — a failover onto the next
                # struggling holder just moves the stall
                cands = _health.BOARD.order_by_health(list(cands))
            for s in cands:
                if s != dead:
                    self._spares.remove(s)
                    return s
        return None

    def _return_spare(self, url: str):
        with self._lock:
            if url and url not in self._spares:
                self._spares.append(url)

    def assignment(self) -> Dict[int, str]:
        """Final shard placement (post-failover): sid -> holder url,
        '' for shards kept locally."""
        return {sid: (self._worker_of[sid].url or "")
                for sid in range(self.total)}

    def _put(self, worker: TargetWorker, item):
        t0 = time.perf_counter()
        waited = False
        while True:
            if self.failed is not None:
                raise SpreadError(
                    f"shard spread failed: {self.failed!r}") \
                    from self.failed
            try:
                worker.q.put(item, timeout=0.05)
                break
            except queue.Full:
                waited = True
        if waited:
            self.blocked_s += time.perf_counter() - t0

    def _pace(self, nbytes: int):
        """Hold the producer under ``rate_mbps``: sleep until the
        cumulative pushed bytes fit the elapsed-time budget. Pacing the
        producer (not the workers) keeps the whole pipeline — encode
        compute included — at the cap, which is the point of running a
        demotion under live traffic."""
        if self.rate_mbps <= 0:
            return
        now = time.perf_counter()
        if self._rate_t0 is None:
            self._rate_t0 = now
        self._rate_bytes += nbytes
        need = self._rate_bytes / (self.rate_mbps * 1e6)
        # sleep until the cumulative budget is caught up — in slices,
        # so a coarse stripe (few big slabs) still honors the cap
        # instead of charging at most one bounded sleep per stripe
        while True:
            spent = time.perf_counter() - self._rate_t0
            if need <= spent:
                break
            time.sleep(min(need - spent, 0.25))

    # -- the stream ---------------------------------------------------------
    def write_stripe(self, data, parity):
        """Route one stripe: row i of ``data``/``parity`` is the next
        ``w`` bytes of shard i / shard k+i."""
        k = data.shape[0]
        w = data.shape[1]
        off = self.offset
        stripe_bytes = 0
        for sid in range(self.total):
            row = data[sid] if sid < k else parity[sid - k]
            chunk = row.tobytes()
            stripe_bytes += len(chunk)
            self._note_buffered(len(chunk))
            self._put(self._worker_of[sid], (sid, off, chunk))
        self.offset = off + w
        with self._lock:
            self.stats.stripes += 1
        self._pace(stripe_bytes)

    def finish(self):
        """Drain every window, join the workers, then finalize all
        shards (atomic ``.part`` -> shard rename on every holder).
        Raises if any push or finalize failed."""
        t0 = time.perf_counter()
        for w in self.workers:
            self._put(w, _SENTINEL)
        for w in self.workers:
            w.join()
        self.blocked_s += time.perf_counter() - t0
        if self.failed is not None:
            raise SpreadError(
                f"shard spread failed: {self.failed!r}") from self.failed
        for sid in range(self.total):
            self.writers[sid].finalize(self._worker_of[sid].url,
                                       self.offset)

    def abort(self):
        """Stop the workers and leave no partial shards: best-effort
        ``.part`` cleanup on every holder and on the local disk."""
        self._fail(SpreadError("spread aborted"))
        for w in self.workers:
            try:
                w.q.put_nowait(_SENTINEL)
            except queue.Full:
                pass
        for w in self.workers:
            w.join(timeout=10.0)
        for sid in range(self.total):
            try:
                self.writers[sid].abort(self._worker_of[sid].url)
            except Exception:  # noqa: BLE001 - best-effort cleanup
                pass
