"""Topology — the master's root cluster state.

Reference weed/topology/topology.go + topology_ec.go +
master_grpc_server.go heartbeat handling: registers volume servers from
heartbeats, tracks per-layout writable volumes and the EC shard map, hands
out file ids (sequencer), and scans for vacuum candidates.
"""

from __future__ import annotations

import os
import random
import threading
from ..util.locks import make_lock, make_rlock
import time
from typing import Dict, List, Optional, Tuple

from ..storage.types import TTL, ReplicaPlacement
from .node import DataCenter, DataNode, VolumeInfo
from .volume_layout import VolumeLayout


class Sequencer:
    """In-memory monotonically increasing file-key generator
    (reference weed/sequence/memory_sequencer.go)."""

    def __init__(self, start: int = 1):
        self._counter = start
        self._lock = make_lock("topology.Sequencer._lock")

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self._counter
            self._counter += count
            return start

    def set_max(self, seen: int):
        with self._lock:
            if seen >= self._counter:
                self._counter = seen + 1


class RaftSequencer(Sequencer):
    """File-key generator whose allocations survive master failover —
    the HA role the reference fills with its etcd sequencer
    (weed/sequence/etcd_sequencer.go), built on this cluster's own raft
    log instead of an external store. Like the etcd variant it grants
    keys in blocks (one consensus round-trip amortized over ``block``
    ids), committing a rising "sequence ceiling" to the log; every
    master applies the ceiling, so a new leader always starts above
    every id any previous leader could have handed out.

    Concurrency contract: ``propose_fn`` blocks until commit and the
    raft apply runs ``apply_ceiling`` (possibly on another thread, or
    reentrantly on this one for a single-node cluster), so this class
    NEVER holds its lock across a propose call.

    A node only hands out ids from grants it proposed itself
    (``_grant_end``): applied ceilings from other leaders advance
    ``_ceiling`` but never open a local allocation window, which is
    what makes failover safe — ids at or below a remote ceiling may
    already be in use.
    """

    def __init__(self, propose_fn, block: int = 10000):
        super().__init__()
        self._propose = propose_fn
        self._block = int(block)
        self._ceiling = 0     # highest committed ceiling (any leader)
        self._grant_end = 0   # top of THIS node's own committed grant
        self._nonce = 0
        # process-unique prefix: nonces ride the replicated log, so two
        # masters' counters must never mint the same nonce (id() +
        # counter can coincide across identical processes — a foreign
        # entry matching a local pending nonce would be adopted as a
        # grant and collide file ids)
        import uuid
        self._nonce_prefix = uuid.uuid4().hex
        self._pending: set = set()  # nonces of my in-flight proposals

    def next_file_id(self, count: int = 1) -> int:
        while True:
            with self._lock:
                if self._counter + count - 1 <= self._grant_end:
                    start = self._counter
                    self._counter += count
                    return start
                need = max(self._block, count)
                target = max(self._ceiling, self._grant_end,
                             self._counter - 1) + need
                self._nonce += 1
                nonce = f"{self._nonce_prefix}-{self._nonce}"
                self._pending.add(nonce)
            # Outside the lock: propose blocks until commit and the
            # apply callback needs the lock. Raises NotLeaderError on a
            # follower — Assign is leader-only, callers redirect.
            # The grant's BASE is decided in apply_ceiling at commit
            # order, not here: a fresh leader may propose before
            # applying the previous leader's entries, and a
            # propose-time base would overlap that leader's grant.
            try:
                self._propose({"type": "sequence_ceiling",
                               "value": target, "nonce": nonce})
            finally:
                with self._lock:
                    self._pending.discard(nonce)
            # loop: if the apply granted us room, allocate; if a
            # foreign ceiling swallowed the whole range (empty grant),
            # re-propose above the now-visible ceiling

    def apply_ceiling(self, value: int, nonce: str = None):
        """Raft apply hook: a committed ceiling from any master. When
        ``nonce`` identifies one of THIS node's in-flight proposals,
        the range (ceiling-before-apply, value] becomes its exclusive
        allocation grant — commit order makes that base authoritative."""
        with self._lock:
            if nonce is not None and nonce in self._pending:
                base = self._ceiling
                if base < value:
                    if base > self._grant_end:
                        # cleared a foreign ceiling: jump the counter
                        # past ids other leaders may have issued
                        self._counter = max(self._counter, base + 1)
                    self._grant_end = max(self._grant_end, value)
            if value > self._ceiling:
                self._ceiling = value

    def ceiling(self) -> int:
        with self._lock:
            return self._ceiling


class EtcdSequencer(Sequencer):
    """File-key generator backed by an EXTERNAL etcd — the reference's
    exact etcd-sequencer slot (weed/sequence/etcd_sequencer.go): grab
    key blocks by compare-and-swapping a shared counter key upward (one
    etcd round trip amortized over `block` ids), so any number of
    masters sharing the etcd can never mint the same id; persist the
    granted ceiling to <meta_dir>/sequencer.dat like the reference, and
    seed etcd up to the file's value at boot (a wiped etcd cannot
    roll ids backwards under a surviving master).

    The raft-backed sequencer (RaftSequencer) fills this HA role
    without an external dependency; this variant exists for operators
    who already run etcd and want the reference's topology.
    """

    KEY = b"/seaweedfs/master/sequence"
    DEFAULT_BLOCK = 500  # reference DefaultEtcdSteps

    def __init__(self, addr: str, user: str = "", password: str = "",
                 meta_dir: str = "", block: int = DEFAULT_BLOCK,
                 api_prefix: str = "/v3"):
        super().__init__()
        # the etcd wire client lives with the etcd filer store; the
        # sequencer is a second consumer of the same gateway protocol
        from ..filer.etcd_store import EtcdClient
        self._client = EtcdClient.from_addr(addr, user=user,
                                            password=password,
                                            api_prefix=api_prefix)
        if user:
            self._client.authenticate()
        self._block = max(1, int(block))
        self._window_end = 0  # exclusive top of OUR granted window
        self._seq_file = os.path.join(meta_dir, "sequencer.dat") \
            if meta_dir else ""
        seed = 0
        if self._seq_file and os.path.exists(self._seq_file):
            try:
                with open(self._seq_file) as f:
                    seed = int(f.read().strip() or "0")
            except ValueError:
                seed = 0
        if seed:
            self._raise_etcd_to(seed)

    # -- etcd CAS ---------------------------------------------------------

    def _read_current(self):
        kvs = self._client.range(self.KEY)
        if not kvs:
            return None
        try:
            return int(kvs[0][1])
        except ValueError:
            raise RuntimeError(
                f"etcd sequence key {self.KEY!r} holds non-integer "
                f"{kvs[0][1]!r}")

    def _raise_etcd_to(self, floor: int):
        """CAS the shared counter up to at least `floor` (no grant)."""
        while True:
            cur = self._read_current()
            if cur is not None and cur >= floor:
                return
            expect = None if cur is None else str(cur).encode()
            if self._client.put_if(self.KEY, expect,
                                   str(floor).encode()):
                return

    def _grant(self, need: int) -> int:
        """CAS a block of `need` ids; returns the window base
        (exclusive — we own (base, base+need])."""
        while True:
            cur = self._read_current()
            base = cur or 0
            expect = None if cur is None else str(cur).encode()
            if self._client.put_if(self.KEY, expect,
                                   str(base + need).encode()):
                if self._seq_file:
                    tmp = self._seq_file + ".tmp"
                    with open(tmp, "w") as f:
                        f.write(str(base + need))
                    os.replace(tmp, self._seq_file)
                return base

    # -- Sequencer --------------------------------------------------------

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            if self._counter + count - 1 < self._window_end:
                start = self._counter
                self._counter += count
                return start
            need = max(self._block, count)
            base = self._grant(need)
            start = max(base + 1, self._counter)
            if start + count - 1 > base + need:
                # local counter (via set_max) sits above even the fresh
                # grant: push etcd up and regrant from there
                self._raise_etcd_to(start - 1)
                base = self._grant(need)
                start = max(base + 1, self._counter)
            self._counter = start + count
            self._window_end = base + need + 1
            return start

    def set_max(self, seen: int):
        with self._lock:
            if seen < self._counter:
                return
            if seen < self._window_end - 1:
                self._counter = seen + 1
                return
            self._counter = seen + 1
            self._window_end = 0  # force a regrant above `seen`
        self._raise_etcd_to(seen)

    def close(self):
        self._client.close()


class Topology:
    def __init__(self, volume_size_limit: int = 30 * 1024 * 1024 * 1024,
                 pulse_seconds: int = 5, sequencer: Sequencer = None):
        self.data_centers: Dict[str, DataCenter] = {}
        self.volume_size_limit = volume_size_limit
        self.pulse_seconds = pulse_seconds
        self.sequencer = sequencer or Sequencer()
        self.layouts: Dict[Tuple[str, str, int], VolumeLayout] = {}
        # vid -> shard_id -> [DataNode] (reference topology_ec.go ecShardMap)
        self.ec_shard_map: Dict[int, List[List[DataNode]]] = {}
        self.ec_collections: Dict[int, str] = {}
        self.max_volume_id = 0
        # optional ("new"|"deleted", vid, url, public_url) callback — the
        # master wires its watch hub here to push location deltas
        self.location_listener = None
        self.lock = make_rlock("topology.lock")

    # -- tree --------------------------------------------------------------
    def get_or_create_dc(self, dc_id: str) -> DataCenter:
        with self.lock:
            dc = self.data_centers.get(dc_id)
            if dc is None:
                dc = DataCenter(dc_id)
                self.data_centers[dc_id] = dc
            return dc

    def all_nodes(self) -> List[DataNode]:
        return [n for dc in self.data_centers.values()
                for n in dc.all_nodes()]

    def find_node(self, url: str) -> Optional[DataNode]:
        for n in self.all_nodes():
            if n.url == url:
                return n
        return None

    # -- layouts -----------------------------------------------------------
    def get_layout(self, collection: str, replication: str,
                   ttl: int) -> VolumeLayout:
        key = (collection, replication, ttl)
        with self.lock:
            layout = self.layouts.get(key)
            if layout is None:
                layout = VolumeLayout(ReplicaPlacement.parse(replication),
                                      ttl, self.volume_size_limit)
                self.layouts[key] = layout
            return layout

    # -- heartbeat registration (reference master_grpc_server.go:20-176) ---
    def register_heartbeat(self, dc_id: str, rack_id: str, ip: str,
                           port: int, public_url: str,
                           max_volume_count: int,
                           volumes: List[dict],
                           ec_shards: Dict[int, int] = None,
                           ec_collections: Dict[int, str] = None,
                           max_file_key: int = 0,
                           fast_url: str = "") -> DataNode:
        with self.lock:
            dc = self.get_or_create_dc(dc_id or "DefaultDataCenter")
            rack = dc.get_or_create_rack(rack_id or "DefaultRack")
            node = rack.get_or_create_node(ip, port, public_url,
                                           max_volume_count)
            node.last_seen = time.time()
            node.fast_url = fast_url
            self.sequencer.set_max(max_file_key)

            infos = [VolumeInfo.from_dict(v) for v in volumes]
            old_vids = set(node.volumes)
            new_vids = {vi.id for vi in infos}
            node.update_volumes(infos)
            for vi in infos:
                self.max_volume_id = max(self.max_volume_id, vi.id)
                layout = self.get_layout(vi.collection, vi.replica_placement,
                                         vi.ttl)
                layout.register_volume(vi, node)
            for vid in old_vids - new_vids:
                for layout in self.layouts.values():
                    layout.unregister_volume(vid, node)
            # push VolumeLocation deltas to watch subscribers (reference
            # master_grpc_server.go:94-152 heartbeat delta broadcast)
            if self.location_listener is not None:
                for vid in new_vids - old_vids:
                    self.location_listener("new", vid, node.url,
                                           node.public_url,
                                           node.fast_url)
                for vid in old_vids - new_vids:
                    self.location_listener("deleted", vid, node.url,
                                           node.public_url,
                                           node.fast_url)

            if ec_shards is not None:
                node.update_ec_shards(ec_shards, ec_collections or {})
                self._sync_ec_shards(node)
            return node

    def apply_heartbeat_delta(self, url: str, new_volumes: List[dict],
                              deleted_volumes: List[int],
                              ec_shards: Dict[int, int] = None,
                              ec_collections: Dict[int, str] = None,
                              max_file_key: int = 0) -> bool:
        """Incremental registration (reference master_grpc_server.go
        IncrementalHeartbeat path). Returns False when the node is
        unknown — the caller must then request a full resync."""
        with self.lock:
            node = self.find_node(url)
            if node is None:
                return False
            node.last_seen = time.time()
            self.sequencer.set_max(max_file_key)
            for v in new_volumes:
                vi = VolumeInfo.from_dict(v)
                was_known = vi.id in node.volumes
                node.volumes[vi.id] = vi
                self.max_volume_id = max(self.max_volume_id, vi.id)
                layout = self.get_layout(vi.collection,
                                         vi.replica_placement, vi.ttl)
                layout.register_volume(vi, node)
                if not was_known and self.location_listener is not None:
                    self.location_listener("new", vi.id, node.url,
                                           node.public_url,
                                           node.fast_url)
            for vid in deleted_volumes:
                was_present = node.volumes.pop(vid, None) is not None
                for layout in self.layouts.values():
                    layout.unregister_volume(vid, node)
                # a delta whose ack was lost gets resent: only a volume
                # we actually knew may broadcast a deletion, or watch
                # subscribers see duplicate events every pulse
                if was_present and self.location_listener is not None:
                    self.location_listener("deleted", vid, node.url,
                                           node.public_url,
                                           node.fast_url)
            if ec_shards is not None:
                node.update_ec_shards(ec_shards, ec_collections or {})
                self._sync_ec_shards(node)
            return True

    def _sync_ec_shards(self, node: DataNode):
        # rebuild this node's contribution to the ec shard map
        for vid, per_shard in self.ec_shard_map.items():
            for holders in per_shard:
                if node in holders:
                    holders.remove(node)
        self._drop_empty_ec_volumes()
        from ..ec.constants import TOTAL_SHARDS
        for vid, bits in node.ec_shards.items():
            per_shard = self.ec_shard_map.setdefault(
                vid, [[] for _ in range(TOTAL_SHARDS)])
            self.ec_collections[vid] = \
                node.ec_shard_collections.get(vid, "")
            self.max_volume_id = max(self.max_volume_id, vid)
            for sid in bits.shard_ids():
                if node not in per_shard[sid]:
                    per_shard[sid].append(node)

    def _drop_empty_ec_volumes(self):
        for vid in [v for v, per_shard in self.ec_shard_map.items()
                    if not any(per_shard)]:
            del self.ec_shard_map[vid]
            self.ec_collections.pop(vid, None)

    def unregister_node(self, node: DataNode):
        """Heartbeat stream broke: drop the node and its volumes."""
        with self.lock:
            for layout in self.layouts.values():
                for vid in list(node.volumes):
                    layout.set_volume_unavailable(vid, node)
            # broadcast the dead node's locations as deleted (reference
            # master_grpc_server.go:24-50 onDisconnect)
            if self.location_listener is not None:
                for vid in list(node.volumes):
                    self.location_listener("deleted", vid, node.url,
                                           node.public_url,
                                           node.fast_url)
            for per_shard in self.ec_shard_map.values():
                for holders in per_shard:
                    if node in holders:
                        holders.remove(node)
            self._drop_empty_ec_volumes()
            if node.rack:
                node.rack.nodes.pop(node.url, None)

    def prune_dead_nodes(self, timeout: float = None) -> List[DataNode]:
        timeout = timeout or self.pulse_seconds * 5
        dead = [n for n in self.all_nodes()
                if time.time() - n.last_seen > timeout]
        for n in dead:
            self.unregister_node(n)
        return dead

    # -- assignment --------------------------------------------------------
    def next_volume_id(self) -> int:
        with self.lock:
            self.max_volume_id += 1
            return self.max_volume_id

    def pick_for_write(self, collection: str, replication: str,
                       ttl: TTL, count: int = 1) -> Optional[tuple]:
        """-> (fid, count, node, all_replica_nodes) or None."""
        layout = self.get_layout(collection, replication, ttl.to_uint32())
        picked = layout.pick_for_write()
        if picked is None:
            return None
        vid, locs = picked
        key = self.sequencer.next_file_id(count)
        cookie = random.getrandbits(32)
        from ..storage.types import format_file_id
        fid = format_file_id(vid, key, cookie)
        return fid, count, locs[0], locs

    def lookup(self, collection: str, vid: int) -> Optional[List[DataNode]]:
        with self.lock:
            for (coll, _, _), layout in self.layouts.items():
                if collection and coll != collection:
                    continue
                locs = layout.lookup(vid)
                if locs:
                    return locs
        # EC volumes resolve via the shard map
        per_shard = self.ec_shard_map.get(vid)
        if per_shard:
            nodes = []
            for holders in per_shard:
                for n in holders:
                    if n not in nodes:
                        nodes.append(n)
            return nodes or None
        return None

    def lookup_ec_shards(self, vid: int) -> Optional[dict]:
        with self.lock:
            per_shard = self.ec_shard_map.get(vid)
            if not per_shard:
                return None
            return {sid: [n.url for n in holders]
                    for sid, holders in enumerate(per_shard) if holders}

    # -- vacuum scan (reference topology_vacuum.go) ------------------------
    def vacuum_candidates(self, garbage_threshold: float = 0.3
                          ) -> List[Tuple[int, List[DataNode]]]:
        out = []
        with self.lock:
            seen = set()
            for node in self.all_nodes():
                for vi in node.volumes.values():
                    if vi.id in seen or vi.read_only:
                        continue
                    if vi.size > 0 and \
                            vi.deleted_byte_count / max(vi.size, 1) \
                            > garbage_threshold:
                        layout = self.get_layout(
                            vi.collection, vi.replica_placement, vi.ttl)
                        locs = layout.lookup(vi.id) or [node]
                        out.append((vi.id, locs))
                        seen.add(vi.id)
        return out

    def to_dict(self) -> dict:
        with self.lock:
            return {
                "max_volume_id": self.max_volume_id,
                "data_centers": {
                    dc.id: {
                        rack.id: {n.url: n.to_dict()
                                  for n in rack.all_nodes()}
                        for rack in dc.racks.values()
                    } for dc in self.data_centers.values()
                },
                "layouts": [layout.to_dict()
                            for layout in self.layouts.values()],
                "ec_volumes": sorted(self.ec_shard_map),
            }
