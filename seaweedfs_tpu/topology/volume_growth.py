"""VolumeGrowth — replica placement and volume creation.

Reference weed/topology/volume_growth.go:26-238: pick servers satisfying
replica placement "xyz" (x other DCs, y other racks in the main DC, z more
servers in the main rack), weighted-randomly by free slots, then create the
volume on each over the admin API.
"""

from __future__ import annotations

import random
from typing import Callable, List, Optional

from ..storage.types import ReplicaPlacement
from .node import DataNode


class NoFreeSlots(Exception):
    pass


def _weighted_pick(candidates, weight_fn, rng: random.Random):
    weights = [max(weight_fn(c), 0.0) for c in candidates]
    total = sum(weights)
    if total <= 0:
        return None
    x = rng.uniform(0, total)
    acc = 0.0
    for c, w in zip(candidates, weights):
        acc += w
        if x <= acc:
            return c
    return candidates[-1]


def find_empty_slots(topo, rp: ReplicaPlacement,
                     preferred_dc: str = "",
                     rng: Optional[random.Random] = None) -> List[DataNode]:
    """Choose rp.copy_count data nodes honoring the placement counts.
    Raises NoFreeSlots when the topology can't satisfy it."""
    rng = rng or random.Random()

    dcs = list(topo.data_centers.values())
    if preferred_dc:
        dcs = [dc for dc in dcs if dc.id == preferred_dc] or dcs

    def rack_feasible(dc, rack) -> bool:
        """Can `rack` be the main rack within `dc`? Needs 1 + same_rack
        distinct free servers here, plus diff_rack other racks in the DC
        with at least one free server each."""
        free_nodes = [n for n in rack.all_nodes() if n.free_space() >= 1]
        if len(free_nodes) < 1 + rp.same_rack:
            return False
        other_racks = [
            r for r in dc.racks.values() if r is not rack
            and any(n.free_space() >= 1 for n in r.all_nodes())]
        return len(other_racks) >= rp.diff_rack

    def dc_ok(dc):
        others = [
            o for o in dcs if o is not dc
            and any(n.free_space() >= 1 for n in o.all_nodes())]
        if len(others) < rp.diff_data_center:
            return False
        return any(rack_feasible(dc, r) for r in dc.racks.values())

    main_dcs = [dc for dc in dcs if dc_ok(dc)]
    if not main_dcs:
        raise NoFreeSlots(f"no data center can host placement {rp}")
    main_dc = _weighted_pick(main_dcs, lambda d: d.free_space(), rng)

    main_racks = [r for r in main_dc.racks.values()
                  if rack_feasible(main_dc, r)]
    if not main_racks:
        raise NoFreeSlots(f"no rack in {main_dc.id} can host placement {rp}")
    main_rack = _weighted_pick(main_racks, lambda r: r.free_space(), rng)

    free_nodes = [n for n in main_rack.all_nodes() if n.free_space() >= 1]
    main_node = _weighted_pick(free_nodes, lambda n: n.free_space(), rng)
    chosen = [main_node]

    # z: more servers in the same rack
    pool = [n for n in free_nodes if n is not main_node]
    for _ in range(rp.same_rack):
        pick = _weighted_pick(pool, lambda n: n.free_space(), rng)
        if pick is None:
            raise NoFreeSlots("not enough servers in main rack")
        chosen.append(pick)
        pool.remove(pick)

    # y: other racks in the main DC
    rack_pool = [r for r in main_dc.racks.values()
                 if r is not main_rack and r.free_space() >= 1]
    for _ in range(rp.diff_rack):
        rack = _weighted_pick(rack_pool, lambda r: r.free_space(), rng)
        if rack is None:
            raise NoFreeSlots("not enough racks in main data center")
        node = _weighted_pick(
            [n for n in rack.all_nodes() if n.free_space() >= 1],
            lambda n: n.free_space(), rng)
        if node is None:
            raise NoFreeSlots("no free server in chosen rack")
        chosen.append(node)
        rack_pool.remove(rack)

    # x: other data centers
    dc_pool = [d for d in dcs if d is not main_dc and d.free_space() >= 1]
    for _ in range(rp.diff_data_center):
        dc = _weighted_pick(dc_pool, lambda d: d.free_space(), rng)
        if dc is None:
            raise NoFreeSlots("not enough data centers")
        node = _weighted_pick(
            [n for n in dc.all_nodes() if n.free_space() >= 1],
            lambda n: n.free_space(), rng)
        if node is None:
            raise NoFreeSlots("no free server in chosen data center")
        chosen.append(node)
        dc_pool.remove(dc)

    return chosen


class VolumeGrowth:
    """Grows a layout by creating volumes on placed nodes via a caller-
    supplied allocator (the master wires this to the volume servers'
    admin HTTP API; tests pass a fake)."""

    def __init__(self, allocate_fn: Callable):
        # allocate_fn(node, vid, collection, replication, ttl) -> bool
        self.allocate_fn = allocate_fn

    def grow_by_count(self, topo, count: int, collection: str,
                      rp: ReplicaPlacement, ttl, preferred_dc: str = ""
                      ) -> int:
        grown = 0
        for _ in range(count):
            nodes = find_empty_slots(topo, rp, preferred_dc)
            vid = topo.next_volume_id()
            ok = all(self.allocate_fn(n, vid, collection, str(rp),
                                      str(ttl)) for n in nodes)
            if ok:
                grown += 1
        return grown
