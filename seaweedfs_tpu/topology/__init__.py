"""topology — master-side cluster state and placement.

DataCenter -> Rack -> DataNode tree with up-adjusting capacity counters,
per-(collection, replication, ttl) volume layouts, growth/placement, and
EC shard maps (reference weed/topology/).
"""

from .node import DataCenter, DataNode, Rack  # noqa: F401
from .topology import Topology  # noqa: F401
from .volume_layout import VolumeLayout  # noqa: F401
from .volume_growth import VolumeGrowth  # noqa: F401
