"""Topology tree nodes (reference weed/topology/node.go, data_center.go,
rack.go, data_node.go).

Volume slots: a node's capacity is max_volume_count; EC shards consume
fractional slots (reference counts one EC shard as 1/10 of a volume —
store.go:99-112).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..ec.constants import DATA_SHARDS
from ..ec.shard_bits import ShardBits


class VolumeInfo:
    """Master's view of one volume replica on one node."""

    __slots__ = ("id", "collection", "size", "file_count", "delete_count",
                 "deleted_byte_count", "read_only", "replica_placement",
                 "ttl", "version", "compact_revision", "modified_at")

    def __init__(self, id: int, collection: str = "", size: int = 0,
                 file_count: int = 0, delete_count: int = 0,
                 deleted_byte_count: int = 0, read_only: bool = False,
                 replica_placement: str = "000", ttl: int = 0,
                 version: int = 3, compact_revision: int = 0,
                 modified_at: float = 0):
        self.id = id
        self.collection = collection
        self.size = size
        self.file_count = file_count
        self.delete_count = delete_count
        self.deleted_byte_count = deleted_byte_count
        self.read_only = read_only
        self.replica_placement = replica_placement
        self.ttl = ttl
        self.version = version
        self.compact_revision = compact_revision
        self.modified_at = modified_at

    @classmethod
    def from_dict(cls, d: dict) -> "VolumeInfo":
        return cls(**{k: d[k] for k in
                      ("id", "collection", "size", "file_count",
                       "delete_count", "deleted_byte_count", "read_only",
                       "replica_placement", "ttl", "version",
                       "compact_revision", "modified_at") if k in d})

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class DataNode:
    """One volume server."""

    def __init__(self, ip: str, port: int, public_url: str = "",
                 max_volume_count: int = 7):
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        # native read plane, when the server advertises one (empty
        # otherwise); read paths prefer it for plain needle GETs
        self.fast_url = ""
        self.max_volume_count = max_volume_count
        self.volumes: Dict[int, VolumeInfo] = {}
        self.ec_shards: Dict[int, ShardBits] = {}  # vid -> bits
        self.ec_shard_collections: Dict[int, str] = {}
        self.last_seen = time.time()
        self.rack: Optional["Rack"] = None

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def id(self) -> str:
        return self.url

    def volume_count(self) -> int:
        return len(self.volumes)

    def ec_shard_count(self) -> int:
        return sum(b.shard_id_count() for b in self.ec_shards.values())

    def free_space(self) -> float:
        """Free volume slots, EC shards counted fractionally
        (reference store.go:99-112 FindFreeLocation)."""
        return self.max_volume_count - len(self.volumes) \
            - self.ec_shard_count() / DATA_SHARDS

    def update_volumes(self, infos: List[VolumeInfo]) -> None:
        self.volumes = {vi.id: vi for vi in infos}

    def add_or_update_volume(self, vi: VolumeInfo) -> bool:
        is_new = vi.id not in self.volumes
        self.volumes[vi.id] = vi
        return is_new

    def delete_volume(self, vid: int) -> None:
        self.volumes.pop(vid, None)

    def update_ec_shards(self, shards: Dict[int, int],
                         collections: Dict[int, str]) -> None:
        self.ec_shards = {vid: ShardBits(bits)
                          for vid, bits in shards.items() if bits}
        self.ec_shard_collections = dict(collections)

    def to_dict(self) -> dict:
        rack = self.rack
        return {
            "url": self.url, "public_url": self.public_url,
            "volumes": len(self.volumes),
            "ec_shards": self.ec_shard_count(),
            "max": self.max_volume_count,
            "free": self.free_space(),
            "last_seen": self.last_seen,
            # placement context for rack-aware shell maintenance
            # (reference command_ec_balance.go works on racks)
            "rack": rack.id if rack else "",
            "dataCenter": rack.data_center.id
            if rack and rack.data_center else "",
        }


class Rack:
    def __init__(self, rack_id: str):
        self.id = rack_id
        self.nodes: Dict[str, DataNode] = {}
        self.data_center: Optional["DataCenter"] = None

    def get_or_create_node(self, ip: str, port: int, public_url: str = "",
                           max_volume_count: int = 7) -> DataNode:
        key = f"{ip}:{port}"
        node = self.nodes.get(key)
        if node is None:
            node = DataNode(ip, port, public_url, max_volume_count)
            node.rack = self
            self.nodes[key] = node
        node.max_volume_count = max_volume_count
        if public_url:
            node.public_url = public_url
        return node

    def free_space(self) -> float:
        return sum(n.free_space() for n in self.nodes.values())

    def all_nodes(self) -> List[DataNode]:
        return list(self.nodes.values())


class DataCenter:
    def __init__(self, dc_id: str):
        self.id = dc_id
        self.racks: Dict[str, Rack] = {}

    def get_or_create_rack(self, rack_id: str) -> Rack:
        rack = self.racks.get(rack_id)
        if rack is None:
            rack = Rack(rack_id)
            rack.data_center = self
            self.racks[rack_id] = rack
        return rack

    def free_space(self) -> float:
        return sum(r.free_space() for r in self.racks.values())

    def all_nodes(self) -> List[DataNode]:
        return [n for r in self.racks.values() for n in r.all_nodes()]
