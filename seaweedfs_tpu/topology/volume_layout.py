"""VolumeLayout — writable-volume tracking per (collection, rp, ttl).

Reference weed/topology/volume_layout.go + collection.go: the master keeps,
for each layout key, which volume ids are writable and where every replica
lives; PickForWrite serves Assign.
"""

from __future__ import annotations

import random
import threading
from ..util.locks import make_rlock
from typing import Dict, List, Optional

from ..storage.types import ReplicaPlacement
from .node import DataNode, VolumeInfo


class VolumeLayout:
    def __init__(self, replica_placement: ReplicaPlacement, ttl: int,
                 volume_size_limit: int):
        self.rp = replica_placement
        self.ttl = ttl
        self.volume_size_limit = volume_size_limit
        self.locations: Dict[int, List[DataNode]] = {}
        self.writables: List[int] = []
        self.readonly: set = set()
        self.oversized: set = set()
        self.lock = make_rlock("volume_layout.lock")

    def register_volume(self, vi: VolumeInfo, node: DataNode):
        with self.lock:
            locs = self.locations.setdefault(vi.id, [])
            if node not in locs:
                locs.append(node)
            if vi.read_only:
                self.readonly.add(vi.id)
            else:
                # heartbeats carry the truth; un-marking readonly on the
                # server must make the volume writable again
                self.readonly.discard(vi.id)
            if vi.size >= self.volume_size_limit:
                self.oversized.add(vi.id)
                self._set_unwritable(vi.id)
            else:
                # writable only when fully replicated and not readonly
                if len(locs) >= self.rp.copy_count and \
                        vi.id not in self.readonly:
                    self._set_writable(vi.id)

    def unregister_volume(self, vid: int, node: DataNode):
        with self.lock:
            locs = self.locations.get(vid)
            if locs and node in locs:
                locs.remove(node)
            if not locs:
                self.locations.pop(vid, None)
                self._set_unwritable(vid)
            elif len(locs) < self.rp.copy_count:
                self._set_unwritable(vid)

    def _set_writable(self, vid: int):
        if vid not in self.writables:
            self.writables.append(vid)

    def _set_unwritable(self, vid: int):
        if vid in self.writables:
            self.writables.remove(vid)

    def set_volume_readonly(self, vid: int, readonly: bool = True):
        with self.lock:
            if readonly:
                self.readonly.add(vid)
                self._set_unwritable(vid)
            else:
                self.readonly.discard(vid)
                locs = self.locations.get(vid, [])
                if len(locs) >= self.rp.copy_count:
                    self._set_writable(vid)

    def set_volume_unavailable(self, vid: int, node: DataNode):
        self.unregister_volume(vid, node)

    def pick_for_write(self) -> Optional[tuple]:
        with self.lock:
            if not self.writables:
                return None
            vid = random.choice(self.writables)
            locs = self.locations.get(vid)
            if not locs:
                self._set_unwritable(vid)
                return None
            return vid, locs

    def lookup(self, vid: int) -> Optional[List[DataNode]]:
        with self.lock:
            locs = self.locations.get(vid)
            return list(locs) if locs else None

    def active_volume_count(self) -> int:
        return len(self.writables)

    def to_dict(self) -> dict:
        with self.lock:
            return {
                "replication": str(self.rp),
                "ttl": self.ttl,
                "writables": list(self.writables),
                "readonly": sorted(self.readonly),
                "volumes": {str(v): [n.url for n in locs]
                            for v, locs in self.locations.items()},
            }
