"""Minimal Raft — leader election + replicated log for master HA.

Reference weed/server/raft_server.go wraps github.com/chrislusf/raft to
elect a master leader and replicate exactly one kind of state: the
topology's max-volume-id counter (weed/topology/cluster_commands.go).
This build implements that slice of Raft directly (election, log
replication, commit, persistence) over the masters' existing HTTP
transport — no external coordination service.

Scope notes (matching the reference's usage, not full Raft):
  * fixed membership (the -peers list), no joint consensus
  * log compaction via state snapshots: applied prefixes collapse into
    a snapshot of the (tiny) state machine once the log passes
    max_log_entries, with an InstallSnapshot RPC for peers whose
    next_index has fallen off the retained suffix — without this every
    proposal re-persists an ever-growing log (O(n) per volume creation)
"""

from __future__ import annotations

import json
import os
import random
import socket
import threading
from ..util.locks import make_rlock
import time
from typing import Callable, Dict, List, Optional

from ..server.http_util import HttpError, post_json

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

ELECTION_TIMEOUT_RANGE = (0.6, 1.2)     # seconds (HTTP-scaled)
HEARTBEAT_INTERVAL = 0.15
RPC_TIMEOUT = 0.5    # must stay well under the election timeout


def _resolve_host(host: str) -> str:
    try:
        return socket.gethostbyname(host)
    except OSError:
        return host


def same_node(a: str, b: str) -> bool:
    """host:port equality tolerant of localhost/127.0.0.1/hostname
    spellings — an exact-string self-match would leave a node in its
    own peer list (phantom quorum member, self-demoting heartbeats)."""
    if a == b:
        return True
    try:
        ha, pa = a.rsplit(":", 1)
        hb, pb = b.rsplit(":", 1)
    except ValueError:
        return False
    return pa == pb and _resolve_host(ha) == _resolve_host(hb)


class NotLeaderError(Exception):
    """Raised for writes on a non-leader (reference raft.NotLeaderError);
    carries the current leader hint."""

    def __init__(self, leader: Optional[str]):
        super().__init__(f"not the raft leader; leader is {leader}")
        self.leader = leader


class RaftNode:
    def __init__(self, node_id: str, peers: List[str],
                 apply_fn: Callable[[dict], None],
                 state_dir: Optional[str] = None,
                 transport: Optional[Callable] = None,
                 snapshot_state_fn: Optional[Callable[[], dict]] = None,
                 restore_fn: Optional[Callable[[dict], None]] = None,
                 max_log_entries: int = 1024):
        """node_id and peers are master urls (host:port). apply_fn is
        called exactly once per committed command, in log order.
        transport(peer, rpc_name, payload) -> reply dict; the default
        POSTs to http://<peer>/raft/<rpc_name>. snapshot_state_fn()
        captures the applied state machine for log compaction;
        restore_fn(state) reinstalls it on a follower receiving an
        InstallSnapshot. Without them the log is kept whole."""
        self.id = node_id
        self.peers = [p for p in peers if not same_node(p, node_id)]
        self.apply_fn = apply_fn
        self.state_dir = state_dir
        self.transport = transport or self._http_transport
        self.snapshot_state_fn = snapshot_state_fn
        self.restore_fn = restore_fn
        self.max_log_entries = int(max_log_entries)

        # persistent state
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log: List[dict] = []        # {"term": t, "command": {...}}
        # compaction base: entries 1..snap_index live only as snap_state
        self.snap_index = 0
        self.snap_term = 0
        self.snap_state: Optional[dict] = None
        self._load_state()

        # volatile
        self.state = FOLLOWER
        self.leader_id: Optional[str] = None
        self.commit_index = self.snap_index  # 1-based; 0 = nothing
        self.last_applied = self.snap_index
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}

        self.lock = make_rlock("raft.lock")
        self._commit_cv = threading.Condition(self.lock)
        self._stop = threading.Event()
        self._election_deadline = self._new_deadline()
        self._inflight: Dict[str, bool] = {}   # one RPC per peer at a time
        self._ticker = threading.Thread(target=self._tick_loop,
                                        daemon=True, name="raft-ticker")

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._ticker.start()
        return self

    def stop(self):
        self._stop.set()

    @property
    def is_leader(self) -> bool:
        with self.lock:
            return self.state == LEADER

    def leader(self) -> Optional[str]:
        with self.lock:
            return self.id if self.state == LEADER else self.leader_id

    # -- log indexing over the snapshot base -------------------------------
    def _last_index(self) -> int:
        return self.snap_index + len(self.log)

    def _entry(self, index: int) -> dict:
        return self.log[index - self.snap_index - 1]

    def _term_at(self, index: int) -> int:
        if index == self.snap_index:
            return self.snap_term
        if index < self.snap_index or index > self._last_index():
            return 0
        return self._entry(index)["term"]

    def _maybe_compact(self):
        """Collapse the applied prefix into a snapshot (call with the
        lock held). The cut is ALWAYS exactly last_applied — the state
        captured by snapshot_state_fn corresponds to precisely that
        apply point, so restore+replay applies every command exactly
        once. A leader therefore either waits for a slightly-behind
        peer (keeps the entries it still needs) or compacts past a
        badly-lagging one, which then catches up via InstallSnapshot."""
        if self.snapshot_state_fn is None:
            return
        if len(self.log) <= self.max_log_entries:
            return
        cut_to = self.last_applied
        if cut_to <= self.snap_index:
            return
        if self.state == LEADER and self.peers and \
                len(self.log) <= 2 * self.max_log_entries:
            # defer for a close peer — but only while the log stays
            # bounded: under sustained writes a peer perpetually a few
            # entries behind must not hold compaction (and the O(log)
            # re-persist per propose) hostage forever. Past 2x the
            # limit the cut proceeds and the peer catches up via
            # InstallSnapshot.
            floor = min(self.match_index.get(p, 0) for p in self.peers)
            if cut_to > floor and \
                    self._last_index() - floor <= self.max_log_entries:
                return  # peer is close: keep its entries, cut later
        self.snap_term = self._term_at(cut_to)
        self.snap_state = self.snapshot_state_fn()
        self.log = self.log[cut_to - self.snap_index:]
        self.snap_index = cut_to
        self._persist()

    # -- persistence -------------------------------------------------------
    def _state_path(self) -> str:
        safe = self.id.replace(":", "_").replace("/", "_")
        return os.path.join(self.state_dir, f"raft-{safe}.json")

    def _load_state(self):
        if not self.state_dir:
            return
        os.makedirs(self.state_dir, exist_ok=True)
        p = self._state_path()
        if os.path.exists(p):
            try:
                with open(p) as f:
                    st = json.load(f)
                self.current_term = st.get("term", 0)
                self.voted_for = st.get("voted_for")
                self.log = st.get("log", [])
                self.snap_index = st.get("snap_index", 0)
                self.snap_term = st.get("snap_term", 0)
                self.snap_state = st.get("snap_state")
                if self.snap_state is not None and \
                        self.restore_fn is not None:
                    self.restore_fn(self.snap_state)
            except (ValueError, OSError):
                pass

    def _persist(self):
        if not self.state_dir:
            return
        p = self._state_path()
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.current_term,
                       "voted_for": self.voted_for,
                       "log": self.log,
                       "snap_index": self.snap_index,
                       "snap_term": self.snap_term,
                       "snap_state": self.snap_state}, f)
        os.replace(tmp, p)

    # -- timers ------------------------------------------------------------
    def _new_deadline(self) -> float:
        return time.monotonic() + random.uniform(*ELECTION_TIMEOUT_RANGE)

    def _tick_loop(self):
        while not self._stop.wait(0.05):
            with self.lock:
                state = self.state
                expired = time.monotonic() >= self._election_deadline
            if state == LEADER:
                self._broadcast_heartbeats()
            elif expired:
                self._run_election()

    # -- election ----------------------------------------------------------
    def _run_election(self):
        with self.lock:
            self.state = CANDIDATE
            self.current_term += 1
            self.voted_for = self.id
            self.leader_id = None
            self._persist()
            term = self.current_term
            last_index = self._last_index()
            last_term = self._term_at(last_index)
            self._election_deadline = self._new_deadline()
        # solicit votes in parallel — serial RPCs against a dead peer
        # would stall past the election timeout and flap leadership
        votes = [1]
        done = threading.Event()

        def ask(peer):
            reply = self._rpc(peer, "request_vote", {
                "term": term, "candidate_id": self.id,
                "last_log_index": last_index,
                "last_log_term": last_term})
            if reply is None:
                return
            with self.lock:
                if reply["term"] > self.current_term:
                    self._become_follower(reply["term"], None)
                    done.set()
                    return
                if self.state != CANDIDATE or self.current_term != term:
                    done.set()
                    return
                if reply.get("vote_granted"):
                    votes[0] += 1
                    if votes[0] * 2 > len(self.peers) + 1:
                        done.set()

        threads = [threading.Thread(target=ask, args=(p,), daemon=True,
                                    name=f"raft-vote-{p}")
                   for p in self.peers]
        for t in threads:
            t.start()
        done.wait(RPC_TIMEOUT + 0.2)
        with self.lock:
            votes = votes[0]
            if self.state == CANDIDATE and self.current_term == term \
                    and votes * 2 > len(self.peers) + 1:
                self.state = LEADER
                self.leader_id = self.id
                nxt = self._last_index() + 1
                self.next_index = {p: nxt for p in self.peers}
                self.match_index = {p: 0 for p in self.peers}
        if self.is_leader:
            self._broadcast_heartbeats()

    def _become_follower(self, term: int, leader: Optional[str]):
        self.state = FOLLOWER
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist()
        if leader:
            self.leader_id = leader
        self._election_deadline = self._new_deadline()

    # -- replication (leader side) ----------------------------------------
    def _broadcast_heartbeats(self):
        """One concurrent replication RPC per peer — a dead peer's
        timeout must never delay the live peers' heartbeats (that would
        expire their election timers and flap leadership)."""
        for peer in self.peers:
            with self.lock:
                if self._inflight.get(peer):
                    continue
                self._inflight[peer] = True

            def run(p=peer):
                try:
                    self._replicate_to(p)
                    self._advance_commit()
                    # compaction waits for close peers; the moment their
                    # match_index catches up (this ack) the deferred cut
                    # can proceed — without this hook a burst of
                    # proposes never compacts (each commit fires while
                    # the slowest ack is still one step behind)
                    with self.lock:
                        self._maybe_compact()
                finally:
                    with self.lock:
                        self._inflight[p] = False
            threading.Thread(target=run, daemon=True,
                             name=f"raft-replicate-{peer}").start()

    def _replicate_to(self, peer: str):
        with self.lock:
            if self.state != LEADER:
                return
            term = self.current_term
            nxt = self.next_index.get(peer, self._last_index() + 1)
            if nxt <= self.snap_index:
                # the peer needs entries we compacted away: ship the
                # snapshot instead, then resume from its last index
                snap = {"term": term, "leader_id": self.id,
                        "snap_index": self.snap_index,
                        "snap_term": self.snap_term,
                        "state": self.snap_state}
            else:
                snap = None
                prev_index = nxt - 1
                prev_term = self._term_at(prev_index)
                entries = self.log[nxt - self.snap_index - 1:]
                commit = self.commit_index
        if snap is not None:
            reply = self._rpc(peer, "install_snapshot", snap)
            if reply is None:
                return
            with self.lock:
                if reply["term"] > self.current_term:
                    self._become_follower(reply["term"], None)
                    return
                if self.state != LEADER or self.current_term != term:
                    return
                self.match_index[peer] = max(
                    self.match_index.get(peer, 0), snap["snap_index"])
                self.next_index[peer] = self.match_index[peer] + 1
            return
        reply = self._rpc(peer, "append_entries", {
            "term": term, "leader_id": self.id,
            "prev_log_index": prev_index, "prev_log_term": prev_term,
            "entries": entries, "leader_commit": commit})
        if reply is None:
            return
        with self.lock:
            if reply["term"] > self.current_term:
                self._become_follower(reply["term"], None)
                return
            if self.state != LEADER or self.current_term != term:
                return
            if reply.get("success"):
                self.match_index[peer] = prev_index + len(entries)
                self.next_index[peer] = self.match_index[peer] + 1
            else:
                self.next_index[peer] = max(1, nxt - 1)

    def _advance_commit(self):
        with self.lock:
            if self.state != LEADER:
                return
            for n in range(self._last_index(), self.commit_index, -1):
                if self._term_at(n) != self.current_term:
                    break
                replicas = 1 + sum(1 for p in self.peers
                                   if self.match_index.get(p, 0) >= n)
                if replicas * 2 > len(self.peers) + 1:
                    self.commit_index = n
                    self._apply_committed()
                    self._commit_cv.notify_all()
                    break

    def _apply_committed(self):
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            self.apply_fn(self._entry(self.last_applied)["command"])
        self._maybe_compact()

    # -- public write path -------------------------------------------------
    def propose(self, command: dict, timeout: float = 5.0) -> int:
        """Append a command, replicate to a majority, apply, return its
        log index. Raises NotLeaderError on a non-leader."""
        with self.lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader())
            self.log.append({"term": self.current_term,
                             "command": command})
            self._persist()
            index = self._last_index()
        if not self.peers:                  # single-node cluster
            with self.lock:
                self.commit_index = index
                self._apply_committed()
            return index
        self._broadcast_heartbeats()
        deadline = time.monotonic() + timeout
        with self._commit_cv:
            while self.commit_index < index:
                left = deadline - time.monotonic()
                if left <= 0 or self._stop.is_set():
                    raise TimeoutError(
                        f"raft commit of index {index} timed out")
                if self.state != LEADER:
                    raise NotLeaderError(self.leader())
                self._commit_cv.wait(min(left, 0.1))
        return index

    # -- RPC handlers (follower side) --------------------------------------
    def handle_request_vote(self, req: dict) -> dict:
        with self.lock:
            term = req["term"]
            if term > self.current_term:
                self._become_follower(term, None)
            granted = False
            if term == self.current_term and \
                    self.voted_for in (None, req["candidate_id"]):
                my_last = self._last_index()
                my_last_term = self._term_at(my_last)
                up_to_date = (
                    req["last_log_term"] > my_last_term or
                    (req["last_log_term"] == my_last_term and
                     req["last_log_index"] >= my_last))
                if up_to_date:
                    granted = True
                    self.voted_for = req["candidate_id"]
                    self._persist()
                    self._election_deadline = self._new_deadline()
            return {"term": self.current_term, "vote_granted": granted}

    def handle_append_entries(self, req: dict) -> dict:
        with self.lock:
            term = req["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if same_node(req["leader_id"], self.id):
                # our own heartbeat reflected back (misconfigured peer
                # list) — stepping down to it would depose us forever
                return {"term": self.current_term, "success": True}
            self._become_follower(term, req["leader_id"])
            prev = req["prev_log_index"]
            entries = req["entries"]
            clamped = False
            if prev < self.snap_index:
                # the window starts inside our compacted prefix — those
                # entries are committed state here; skip past them. The
                # leader's prev_log_term describes its ORIGINAL prev
                # index, not the clamped boundary, so no term check
                # applies after clamping (the boundary is our own
                # committed snapshot by definition) — comparing would
                # wrongly reject every retransmission and walk
                # next_index backwards forever.
                skip = self.snap_index - prev
                entries = entries[skip:] if skip < len(entries) else []
                prev = self.snap_index
                clamped = True
            if prev > self._last_index() or (
                    not clamped and prev > 0 and
                    self._term_at(prev) != req.get("prev_log_term", 0)):
                return {"term": self.current_term, "success": False}
            if entries:
                # Raft §5.3: truncate only from the first index where the
                # terms conflict, then append the genuinely new suffix — a
                # delayed/duplicated AppendEntries carrying an older
                # overlapping window must not wipe entries the follower
                # already acknowledged (possibly committed)
                changed = False
                for i, e in enumerate(entries):
                    pos = prev + i - self.snap_index  # 0-based log slot
                    if pos < len(self.log):
                        if self.log[pos]["term"] != e["term"]:
                            self.log = self.log[:pos] + entries[i:]
                            changed = True
                            break
                    else:
                        self.log = self.log + entries[i:]
                        changed = True
                        break
                if changed:
                    self._persist()
            if req["leader_commit"] > self.commit_index:
                self.commit_index = min(req["leader_commit"],
                                        self._last_index())
                self._apply_committed()
            return {"term": self.current_term, "success": True}

    def handle_install_snapshot(self, req: dict) -> dict:
        """Reinstall a compacted leader's state (Raft §7 InstallSnapshot,
        minimal form: the whole state machine rides in one message —
        it is a single counter here)."""
        with self.lock:
            term = req["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            self._become_follower(term, req["leader_id"])
            snap_index = int(req["snap_index"])
            snap_term = int(req["snap_term"])
            if snap_index <= self.snap_index:
                return {"term": self.current_term, "success": True}
            if snap_index < self._last_index() and \
                    self._term_at(snap_index) == snap_term:
                # our suffix continues the snapshot's branch: keep it
                self.log = self.log[snap_index - self.snap_index:]
            else:
                # conflicting (stale-branch) or absent suffix: Raft §7
                # discards the entire log — stitching a different
                # branch past the boundary fabricates an impossible log
                self.log = []
            self.snap_index = snap_index
            self.snap_term = snap_term
            self.snap_state = req.get("state")
            if self.snap_state is not None and self.restore_fn is not None:
                self.restore_fn(self.snap_state)
            self.commit_index = max(self.commit_index, snap_index)
            self.last_applied = max(self.last_applied, snap_index)
            self._persist()
            return {"term": self.current_term, "success": True}

    # -- transport ---------------------------------------------------------
    def _http_transport(self, peer: str, rpc: str, payload: dict):
        return post_json(f"http://{peer}/raft/{rpc}", payload,
                         timeout=RPC_TIMEOUT)

    def _rpc(self, peer: str, rpc: str, payload: dict) -> Optional[dict]:
        try:
            return self.transport(peer, rpc, payload)
        except (HttpError, OSError):
            return None

    def status(self) -> dict:
        with self.lock:
            return {"id": self.id, "state": self.state,
                    "term": self.current_term,
                    "leader": self.leader(),
                    "log_length": len(self.log),
                    "snap_index": self.snap_index,
                    "commit_index": self.commit_index,
                    "peers": self.peers}
