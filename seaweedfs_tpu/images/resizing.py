"""Resize + EXIF-orientation fix on image reads.

Reference weed/images/resizing.go + orientation.go, hooked into the
volume server GET path (volume_server_handlers_read.go resizes when
?width/?height/?mode are present; needle.go:98-103 fixes JPEG
orientation at write time — this build applies it on read, same visible
result without rewriting stored bytes). Pillow-backed; when Pillow is
missing the hooks degrade to passthrough.
"""

from __future__ import annotations

import io
from typing import Optional, Tuple

try:
    from PIL import Image, ImageOps
    _HAVE_PIL = True
except ImportError:          # pragma: no cover - PIL is in this build
    _HAVE_PIL = False

RESIZABLE = ("image/jpeg", "image/png", "image/gif", "image/webp")


def _format_of(mime: str) -> str:
    return {"image/jpeg": "JPEG", "image/png": "PNG",
            "image/gif": "GIF", "image/webp": "WEBP"}.get(mime, "PNG")


def fix_orientation(data: bytes, mime: str = "image/jpeg") -> bytes:
    """Bake the EXIF orientation into the pixels (reference
    FixJpgOrientation)."""
    if not _HAVE_PIL or mime != "image/jpeg":
        return data
    try:
        img = Image.open(io.BytesIO(data))
        fixed = ImageOps.exif_transpose(img)
        if fixed is img:
            return data
        out = io.BytesIO()
        fixed.save(out, format="JPEG", quality=90)
        return out.getvalue()
    except Exception:        # noqa: BLE001 — never break a read
        return data


def resize_image(data: bytes, mime: str, width: int = 0, height: int = 0,
                 mode: str = "") -> Tuple[bytes, str]:
    """Resize per the reference's semantics (Resized,
    resizing.go:17-48): mode 'fit' preserves aspect ratio within the
    box (default when both dims given), 'fill' crops to fill the box
    exactly, one-dimension scales proportionally. Returns
    (bytes, mime); passthrough when not resizable."""
    if not _HAVE_PIL or mime not in RESIZABLE or (not width and
                                                  not height):
        return data, mime
    try:
        img = Image.open(io.BytesIO(data))
        w0, h0 = img.size
        if width and height:
            if mode == "fill":
                img = ImageOps.fit(img, (width, height))
            else:
                img.thumbnail((width, height))
        elif width:
            img = img.resize((width, max(1, h0 * width // w0)))
        else:
            img = img.resize((max(1, w0 * height // h0), height))
        out = io.BytesIO()
        save_kwargs = {"quality": 90} if mime == "image/jpeg" else {}
        if img.mode in ("P", "RGBA") and mime == "image/jpeg":
            img = img.convert("RGB")
        img.save(out, format=_format_of(mime), **save_kwargs)
        return out.getvalue(), mime
    except Exception:        # noqa: BLE001 — never break a read
        return data, mime
