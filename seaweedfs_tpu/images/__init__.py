"""Image ops on the read path (reference weed/images/)."""

from .resizing import fix_orientation, resize_image  # noqa: F401
