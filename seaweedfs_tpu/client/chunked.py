"""Client-side chunk-manifest large files.

Reference weed/operation/submit.go:114-230 (SubmitFiles splitting a
>maxMB upload into chunk needles + a manifest needle flagged
FlagIsChunkManifest) and weed/operation/chunked_file.go (the manifest
codec + chunked reader). The raw volume path caps a needle at 4GB and a
volume's free space bounds a single blob; the manifest indirection
stripes one logical file over many fids — potentially many volumes —
while keeping a single public fid.

Manifest JSON (stored as the flagged needle's payload):
    {"name": ..., "mime": ..., "size": N,
     "chunks": [{"fid": ..., "offset": N, "size": N}, ...]}
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from ..server.http_util import HttpError, http_call, post_multipart
from . import operation as op


class ChunkInfo:
    __slots__ = ("fid", "offset", "size")

    def __init__(self, fid: str, offset: int, size: int):
        self.fid = fid
        self.offset = offset
        self.size = size


class ChunkManifest:
    def __init__(self, name: str = "", mime: str = "", size: int = 0,
                 chunks: Optional[List[ChunkInfo]] = None):
        self.name = name
        self.mime = mime
        self.size = size
        self.chunks = chunks or []

    def to_json(self) -> bytes:
        return json.dumps({
            "name": self.name, "mime": self.mime, "size": self.size,
            "chunks": [{"fid": c.fid, "offset": c.offset, "size": c.size}
                       for c in self.chunks]}).encode()

    @classmethod
    def from_json(cls, blob: bytes) -> "ChunkManifest":
        d = json.loads(blob.decode())
        return cls(d.get("name", ""), d.get("mime", ""),
                   int(d.get("size", 0)),
                   [ChunkInfo(c["fid"], int(c["offset"]), int(c["size"]))
                    for c in d.get("chunks", [])])


def submit_chunked(master_url: str, data: bytes, filename: str = "",
                   collection: str = "", replication: str = "",
                   ttl: str = "", chunk_size: int = 32 << 20,
                   content_type: str = "") -> str:
    """Split ``data`` into chunk needles and store a manifest needle;
    returns the manifest's fid (the file's public id). Chunks that
    landed before a failure are deleted on the way out."""
    manifest = ChunkManifest(name=filename, mime=content_type,
                             size=len(data))
    uploaded: List[str] = []
    try:
        for off in range(0, len(data), chunk_size) or [0]:
            piece = data[off:off + chunk_size]
            a = op.assign(master_url, collection=collection,
                          replication=replication, ttl=ttl)
            op.upload(a["url"], a["fid"], piece,
                      filename=f"{filename}_chunk_{off}",
                      content_type="application/octet-stream",
                      ttl=ttl, jwt=a.get("auth", ""))
            uploaded.append(a["fid"])
            manifest.chunks.append(ChunkInfo(a["fid"], off, len(piece)))
        main = op.assign(master_url, collection=collection,
                         replication=replication, ttl=ttl)
        target = f"http://{main['url']}/{main['fid']}?cm=true"
        if ttl:
            target += f"&ttl={ttl}"
        headers = {"Authorization": f"Bearer {main['auth']}"} \
            if main.get("auth") else None
        post_multipart(target, filename or "manifest",
                       manifest.to_json(), "application/json",
                       headers=headers)
        return main["fid"]
    except Exception:
        for fid in uploaded:  # don't leak chunk needles on failure
            try:
                op.delete_file(master_url, fid)
            except Exception:  # noqa: BLE001 - best-effort cleanup
                pass
        raise


def read_chunked_file(master_url: str, fid: str,
                      cache: Optional["op.VidCache"] = None) -> bytes:
    """Fetch a manifest fid and reassemble the logical file (the volume
    server also resolves manifests server-side; this is the client-side
    reader the reference keeps in chunked_file.go)."""
    manifest = ChunkManifest.from_json(_raw_read(master_url, fid, cache))
    out = bytearray(manifest.size)
    for c in manifest.chunks:
        piece = op.read_file(master_url, c.fid, cache=cache)
        out[c.offset:c.offset + len(piece)] = piece
    return bytes(out)


def _raw_read(master_url: str, fid: str, cache=None) -> bytes:
    from ..storage.types import parse_file_id
    vid, _, _ = parse_file_id(fid)
    urls = cache.lookup(vid) if cache else op.lookup(master_url, vid)
    last: Optional[Exception] = None
    for u in urls:
        try:
            return http_call("GET", f"http://{u}/{fid}?cm=false")
        except HttpError as e:
            last = e
    raise last or HttpError(404, f"no locations for {fid}")
