"""Client operations: assign, upload, lookup, delete.

Reference weed/operation/{assign_file_id,upload_content,lookup,
delete_content}.go and wdclient/vid_map.go (the TTL'd volume-location
cache).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..server.http_util import HttpError, get_json, http_call, post_multipart


def assign(master_url: str, count: int = 1, collection: str = "",
           replication: str = "", ttl: str = "",
           data_center: str = "") -> dict:
    q = f"count={count}"
    if collection:
        q += f"&collection={collection}"
    if replication:
        q += f"&replication={replication}"
    if ttl:
        q += f"&ttl={ttl}"
    if data_center:
        q += f"&dataCenter={data_center}"
    return get_json(f"http://{master_url}/dir/assign?{q}")


def expand_batch_fids(fid: str, granted: int):
    """The fid_N suffix convention for `?count=` batch assigns: the
    master grants `granted` sequential keys addressed as fid, fid_1,
    fid_2, ... (same volume + cookie). Both benchmark modes and any
    batch uploader must spell the suffixes identically — this is the
    single owner of that convention."""
    for i in range(granted):
        yield fid if i == 0 else f"{fid}_{i}"


def upload(url: str, fid: str, data: bytes, filename: str = "",
           content_type: str = "",
           ttl: str = "", jwt: str = "") -> dict:
    if not content_type:
        # guess from the filename like the reference's clients do —
        # mime drives read-side features (image resize, browser render);
        # an explicit octet-stream is respected
        import mimetypes
        guessed, _ = mimetypes.guess_type(filename or "")
        content_type = guessed or "application/octet-stream"
    target = f"http://{url}/{fid}"
    if ttl:
        target += f"?ttl={ttl}"
    headers = {"Authorization": f"Bearer {jwt}"} if jwt else None
    return post_multipart(target, filename, data, content_type,
                          headers=headers)


def upload_data(master_url: str, data: bytes, filename: str = "",
                collection: str = "", replication: str = "",
                ttl: str = "",
                content_type: str = "") -> str:
    """Assign + upload; returns the fid."""
    a = assign(master_url, collection=collection, replication=replication,
               ttl=ttl)
    # prefer the holder's native write plane; off-fast-path shapes
    # (ttl query, pairs, raw bodies) 307 back to the Python server and
    # http_call follows 307s with the method+body preserved
    upload(a.get("fastUrl") or a["url"], a["fid"], data, filename,
           content_type, ttl, jwt=a.get("auth", ""))
    return a["fid"]


class VidCache:
    """Volume-id -> locations cache (reference lookup_vid_cache.go /
    vid_map.go).

    With ``watch=True`` the cache rides the master's push channel
    (client/vid_map.py long-polling /cluster/watch) — routes are never
    staler than one master pulse, and the TTL'd /dir/lookup below is
    only the fallback while the map warms up or the master is away."""

    def __init__(self, master_url: str, ttl_seconds: float = 10.0,
                 watch: bool = False):
        self.master_url = master_url
        self.ttl = ttl_seconds
        self._cache: Dict[int, tuple] = {}
        self._vid_map = None
        if watch:
            from .vid_map import shared_vid_map
            self._vid_map = shared_vid_map(master_url)

    def lookup(self, vid: int) -> List[str]:
        if self._vid_map is not None:
            urls = self._vid_map.lookup(vid)
            if urls is not None:
                return urls
        hit = self._cache.get(vid)
        if hit and time.time() - hit[0] < self.ttl:
            return [l["url"] for l in hit[1]]
        return [l["url"] for l in self._lookup_locations(vid)]

    def lookup_read(self, vid: int) -> List[str]:
        """Read-preferred routes: each holder's native read plane
        (fastUrl) first, then its regular url as the fallback."""
        if self._vid_map is not None:
            urls = self._vid_map.lookup_read(vid)
            if urls is not None:
                return urls
        hit = self._cache.get(vid)
        if hit and time.time() - hit[0] < self.ttl:
            locs = hit[1]
        else:
            locs = self._lookup_locations(vid)
        from .vid_map import _read_routes
        return _read_routes(locs)

    def _lookup_locations(self, vid: int) -> List[dict]:
        out = get_json(f"http://{self.master_url}/dir/lookup?volumeId={vid}")
        locs = out.get("locations", [])
        self._cache[vid] = (time.time(), locs)
        return locs

    def invalidate(self, vid: int, failed_urls=()):
        """Drop cached routes; with ``failed_urls`` the push-updated
        vid map also discards those holders (a bare TTL-cache pop
        cannot help a watch-backed cache — the map would keep serving
        the same stale route until the master's delta lands)."""
        self._cache.pop(vid, None)
        if self._vid_map is not None:
            for url in failed_urls:
                self._vid_map.discard_url(vid, url)


def lookup(master_url: str, vid: int) -> List[str]:
    out = get_json(f"http://{master_url}/dir/lookup?volumeId={vid}")
    return [l["url"] for l in out.get("locations", [])]


def lookup_read(master_url: str, vid: int) -> List[str]:
    from .vid_map import _read_routes
    out = get_json(f"http://{master_url}/dir/lookup?volumeId={vid}")
    return _read_routes(out.get("locations", []))


def read_file(master_url: str, fid: str,
              cache: Optional[VidCache] = None) -> bytes:
    return read_file_named(master_url, fid, cache)[0]


def read_file_named(master_url: str, fid: str,
                    cache: Optional[VidCache] = None):
    """Fetch a needle and its stored filename (from Content-Disposition;
    reference download.go names output files this way).
    -> (data, name_or_empty). read_file delegates here so the lookup/
    failover loop exists once."""
    import email.message as _em

    from ..server.http_util import http_get_with_headers
    from ..storage.types import parse_file_id
    vid, _, _ = parse_file_id(fid)
    # reads prefer a holder's native plane; deletes/writes never do (the
    # pooled client only follows redirects for GET/HEAD)
    urls = cache.lookup_read(vid) if cache \
        else lookup_read(master_url, vid)
    last_err = None
    for u in urls:
        try:
            data, headers = http_get_with_headers(f"http://{u}/{fid}")
            cd = {k.lower(): v for k, v in headers.items()}.get(
                "content-disposition", "")
            # stdlib header parsing handles quoting/escapes that a
            # naive regex would truncate on
            msg = _em.Message()
            msg["content-disposition"] = cd
            name = msg.get_param("filename",
                                 header="content-disposition") or ""
            return data, (name if isinstance(name, str) else "")
        except HttpError as e:
            last_err = e
    raise last_err or HttpError(404, f"no locations for {fid}")


def delete_file(master_url: str, fid: str,
                cache: Optional[VidCache] = None,
                jwt: str = "") -> bool:
    from ..storage.types import parse_file_id
    vid, _, _ = parse_file_id(fid)
    urls = cache.lookup(vid) if cache else lookup(master_url, vid)
    headers = {"Authorization": f"Bearer {jwt}"} if jwt else None
    ok = False
    for u in urls:
        try:
            http_call("DELETE", f"http://{u}/{fid}", headers=headers)
            ok = True
            break  # server fans out to replicas itself
        except HttpError:
            continue
    return ok
