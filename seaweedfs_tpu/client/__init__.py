"""client — operations library (reference weed/operation + weed/wdclient)."""

from .operation import (  # noqa: F401
    assign, delete_file, lookup, upload, upload_data, VidCache,
)
