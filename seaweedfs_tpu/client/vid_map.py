"""Client-side push-updated volume-location map.

Reference weed/wdclient/masterclient.go:45-121 (KeepConnected loop) +
vid_map.go:23-28: the client holds a live stream from the master and
applies VolumeLocation new/deleted deltas, so routing never serves a
location more stale than one master pulse — unlike the 10s TTL'd
lookup cache it replaces as the primary source.

One daemon poller per master URL is shared process-wide
(``shared_vid_map``); every VidCache(watch=True) rides the same map.
"""

from __future__ import annotations

import threading
from ..util.locks import make_lock
import time
from typing import Dict, List, Optional
from ..util import config


class VidMap:
    POLL_TIMEOUT = 20.0
    MAX_CONSECUTIVE_FAILURES = 15  # then park until a lookup revives us

    def __init__(self, master_url: str):
        self.master_url = master_url
        self._locations: Dict[int, List[dict]] = {}
        self._seq = 0
        self._lock = make_lock("vid_map._lock")
        self._ready = threading.Event()  # first snapshot applied
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_start = 0.0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "VidMap":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._last_start = time.monotonic()
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name=f"vidmap-{self.master_url}")
                self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    # -- lookup ------------------------------------------------------------
    def lookup(self, vid: int) -> Optional[List[str]]:
        """Pushed locations, or None when the map isn't live (caller
        falls back to a /dir/lookup). A parked poller is revived here."""
        if not self._ready.is_set():
            if self._thread is None or not self._thread.is_alive():
                if time.monotonic() - self._last_start > 5:
                    self.start()
            return None
        with self._lock:
            locs = self._locations.get(vid)
            return [l["url"] for l in locs] if locs else None

    def lookup_read(self, vid: int) -> Optional[List[str]]:
        """Like lookup(), but read-preferred: each holder's native read
        plane (fastUrl) first, then its regular url as the fallback —
        a plane hiccup must degrade to the Python server, never make a
        healthy holder unreachable."""
        if not self._ready.is_set():
            if self._thread is None or not self._thread.is_alive():
                if time.monotonic() - self._last_start > 5:
                    self.start()
            return None
        with self._lock:
            locs = self._locations.get(vid)
            if not locs:
                return None
            return _read_routes(locs)

    def known(self, vid: int) -> bool:
        with self._lock:
            return vid in self._locations

    def discard_url(self, vid: int, url: str):
        """Drop one route a caller just observed failing. The push
        stream remains authoritative (the master's next delta restores
        reality); this only stops retries of a dead route in the
        window before that delta arrives. A failing fast plane strips
        only the fastUrl (the holder's Python server stays routable);
        a failing holder url drops the holder. An emptied entry is
        removed so lookups fall back to a direct /dir/lookup."""
        with self._lock:
            locs = self._locations.get(vid)
            if not locs:
                return
            kept = []
            for l in locs:
                if l["url"] == url:
                    continue
                if l.get("fastUrl") == url:
                    l = {k: v for k, v in l.items() if k != "fastUrl"}
                kept.append(l)
            if kept:
                self._locations[vid] = kept
            else:
                del self._locations[vid]

    # -- poll loop ---------------------------------------------------------
    def _apply(self, out: dict):
        with self._lock:
            if out.get("reset"):
                self._locations = {
                    int(v): list(locs)
                    for v, locs in (out.get("locations") or {}).items()}
            for ev in out.get("events") or []:
                vid = int(ev["vid"])
                entry = {"url": ev["url"],
                         "publicUrl": ev.get("publicUrl", ev["url"])}
                if ev.get("fastUrl"):
                    entry["fastUrl"] = ev["fastUrl"]
                locs = self._locations.setdefault(vid, [])
                if ev["type"] == "new":
                    if all(l["url"] != entry["url"] for l in locs):
                        locs.append(entry)
                else:
                    locs[:] = [l for l in locs if l["url"] != entry["url"]]
                    if not locs:
                        del self._locations[vid]
            self._seq = int(out.get("seq", self._seq))
        self._ready.set()

    def _loop(self):
        from ..server.http_util import get_json
        failures = 0
        while not self._stop.is_set():
            try:
                out = get_json(
                    f"http://{self.master_url}/cluster/watch"
                    f"?since={self._seq}&timeout={self.POLL_TIMEOUT}",
                    timeout=self.POLL_TIMEOUT + 10)
                self._apply(out)
                failures = 0
            except Exception:  # noqa: BLE001 - master down/unreachable
                failures += 1
                self._ready.clear()  # stale map must not serve routes
                self._seq = 0        # resync with a snapshot on recovery
                if failures >= self.MAX_CONSECUTIVE_FAILURES:
                    return           # park; a later lookup() revives us
                self._stop.wait(max(0.01, config.retry_backoff_s(
                    min(2.0, 0.2 * failures))))


def _read_routes(locs) -> List[str]:
    """Per holder: fastUrl (when advertised) then the regular url, so
    reads prefer the native plane but always have the Python fallback."""
    out: List[str] = []
    for l in locs:
        fast = l.get("fastUrl")
        if fast:
            out.append(fast)
        out.append(l["url"])
    return out


_shared: Dict[str, VidMap] = {}
_shared_lock = make_lock("vid_map._shared_lock")


def shared_vid_map(master_url: str) -> VidMap:
    with _shared_lock:
        vm = _shared.get(master_url)
        if vm is None:
            vm = _shared[master_url] = VidMap(master_url)
        return vm.start()
