"""In-memory filer store (sorted dict per directory).

The embedded-default analog of the reference's leveldb store
(weed/filer2/leveldb/leveldb_store.go) for tests and single-process runs.
"""

from __future__ import annotations

import bisect
import threading
from ..util.locks import make_rlock
from typing import Dict, List, Optional

from .entry import Entry
from .filerstore import FilerStore, register_store


@register_store
class MemoryStore(FilerStore):
    name = "memory"

    def initialize(self, **options):
        self._lock = make_rlock("memory_store._lock")
        self._entries: Dict[str, bytes] = {}
        # dir -> sorted list of child names (listing index)
        self._children: Dict[str, List[str]] = {}

    def _index_add(self, entry: Entry):
        names = self._children.setdefault(entry.dir_name, [])
        i = bisect.bisect_left(names, entry.name)
        if i >= len(names) or names[i] != entry.name:
            names.insert(i, entry.name)

    def _index_remove(self, full_path: str):
        import posixpath
        d, n = posixpath.dirname(full_path) or "/", \
            posixpath.basename(full_path)
        names = self._children.get(d)
        if names:
            i = bisect.bisect_left(names, n)
            if i < len(names) and names[i] == n:
                names.pop(i)

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            self._entries[entry.full_path] = entry.encode()
            self._index_add(entry)

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, full_path: str) -> Optional[Entry]:
        with self._lock:
            data = self._entries.get(full_path)
            if data is None:
                return None
            return Entry.decode(full_path, data)

    def delete_entry(self, full_path: str) -> None:
        with self._lock:
            self._entries.pop(full_path, None)
            self._index_remove(full_path)

    def delete_folder_children(self, full_path: str) -> None:
        with self._lock:
            prefix = full_path.rstrip("/") + "/"
            doomed = [p for p in self._entries if p.startswith(prefix)]
            for p in doomed:
                self._entries.pop(p, None)
                self._index_remove(p)

    def list_directory_entries(self, dir_path: str, start_file_name: str,
                               inclusive: bool,
                               limit: int) -> List[Entry]:
        with self._lock:
            dir_path = dir_path.rstrip("/") or "/"
            names = self._children.get(dir_path, [])
            if start_file_name:
                i = bisect.bisect_left(names, start_file_name)
                if (i < len(names) and names[i] == start_file_name
                        and not inclusive):
                    i += 1
            else:
                i = 0
            out: List[Entry] = []
            base = dir_path.rstrip("/")
            for name in names[i:]:
                if len(out) >= limit:
                    break
                full = f"{base}/{name}"
                data = self._entries.get(full)
                if data is not None:
                    out.append(Entry.decode(full, data))
            return out
