"""SQLite filer store — the durable embedded store.

Plays the role of the reference's SQL stores (weed/filer2/abstract_sql/
abstract_sql_store.go with mysql/postgres drivers): one table keyed by
(directory, name) with the encoded entry as a blob, listings as ordered
range scans. SQLite is in the stdlib, so this is the default durable
store the way leveldb is for the reference.
"""

from __future__ import annotations

import posixpath
import sqlite3
import threading
from ..util.locks import make_rlock
from typing import List, Optional

from .entry import Entry
from .filerstore import FilerStore, register_store


@register_store
class SqliteStore(FilerStore):
    name = "sqlite"

    def initialize(self, path: str = ":memory:", **options):
        self._lock = make_rlock("sqlite_store._lock")
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS filemeta ("
            " directory TEXT NOT NULL,"
            " name TEXT NOT NULL,"
            " meta BLOB,"
            " PRIMARY KEY (directory, name))")
        self._db.execute(
            "CREATE INDEX IF NOT EXISTS idx_dir ON filemeta (directory)")
        self._db.commit()

    @staticmethod
    def _split(full_path: str):
        return (posixpath.dirname(full_path) or "/",
                posixpath.basename(full_path))

    def insert_entry(self, entry: Entry) -> None:
        d, n = self._split(entry.full_path)
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO filemeta (directory, name, meta) "
                "VALUES (?, ?, ?)", (d, n, entry.encode()))
            self._db.commit()

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, full_path: str) -> Optional[Entry]:
        d, n = self._split(full_path)
        with self._lock:
            row = self._db.execute(
                "SELECT meta FROM filemeta WHERE directory=? AND name=?",
                (d, n)).fetchone()
        if row is None:
            return None
        return Entry.decode(full_path, row[0])

    def delete_entry(self, full_path: str) -> None:
        d, n = self._split(full_path)
        with self._lock:
            self._db.execute(
                "DELETE FROM filemeta WHERE directory=? AND name=?", (d, n))
            self._db.commit()

    def delete_folder_children(self, full_path: str) -> None:
        prefix = full_path.rstrip("/") + "/"
        # escape LIKE wildcards in the path itself, else "/a_b" would
        # also delete children of "/axb"
        escaped = prefix.replace("\\", "\\\\").replace("%", "\\%") \
                        .replace("_", "\\_")
        with self._lock:
            self._db.execute(
                "DELETE FROM filemeta WHERE directory=? "
                "OR directory LIKE ? ESCAPE '\\'",
                (full_path.rstrip("/") or "/", escaped + "%"))
            self._db.commit()

    def list_directory_entries(self, dir_path: str, start_file_name: str,
                               inclusive: bool,
                               limit: int) -> List[Entry]:
        dir_path = dir_path.rstrip("/") or "/"
        op = ">=" if inclusive else ">"
        with self._lock:
            if start_file_name:
                rows = self._db.execute(
                    f"SELECT name, meta FROM filemeta WHERE directory=? "
                    f"AND name {op} ? ORDER BY name LIMIT ?",
                    (dir_path, start_file_name, limit)).fetchall()
            else:
                rows = self._db.execute(
                    "SELECT name, meta FROM filemeta WHERE directory=? "
                    "ORDER BY name LIMIT ?", (dir_path, limit)).fetchall()
        base = dir_path.rstrip("/")
        return [Entry.decode(f"{base}/{name}", meta) for name, meta in rows]

    def close(self):
        with self._lock:
            self._db.close()
