"""Cassandra filer store over a from-scratch CQL binary client.

Reference weed/filer2/cassandra/cassandra_store.go (gocql): table
`filemeta (directory, name, meta)` with DIRECTORY as the partition key
and NAME clustering — a directory listing is one partition's
clustering-ordered slice, exactly Cassandra's sweet spot.

The client speaks CQL native protocol v4 over one TCP connection with
zero dependencies: STARTUP, SASL PLAIN authentication
(PasswordAuthenticator), QUERY with inline literals (quote-doubling;
blobs as 0x… constants), and RESULT rows parsing (global-table-spec
and per-column metadata shapes). Inserts are upserts by Cassandra
semantics, so insert/update share one statement.

One semantic bridge: this filer's delete_folder_children contract is
RECURSIVE, but a partition-keyed table cannot prefix-scan its
partition keys. The filer materializes every parent directory entry
(filer.py ensure_parents), so the store recurses the directory tree
it can SEE — list children, descend into child directories, then drop
each directory's partition — the same walk the reference FILER does
for its recursive deletes (filer_delete_entry.go), pushed into the
store to honor the contract the other five backends implement with
key-space prefix deletes.
"""

from __future__ import annotations

import posixpath
import socket
import struct
import threading
from ..util.locks import make_lock
from typing import List, Optional, Tuple

from .entry import Entry
from .filerstore import FilerStore, register_store

OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_AUTHENTICATE = 0x03
OP_QUERY = 0x07
OP_RESULT = 0x08
OP_AUTH_RESPONSE = 0x0F
OP_AUTH_SUCCESS = 0x10

RESULT_VOID = 0x01
RESULT_ROWS = 0x02

META_GLOBAL_TABLES_SPEC = 0x01
META_HAS_MORE_PAGES = 0x02
META_NO_METADATA = 0x04


class CassandraError(Exception):
    """Server ERROR frame — not fixable by reconnecting."""


class CassandraConnectionError(CassandraError):
    """Torn transport — retriable with a reconnect."""


def cql_escape(s: str) -> str:
    """CQL string literals escape by quote-doubling only."""
    return s.replace("'", "''")


class CqlClient:
    """Minimal CQL v4 client: one connection, one in-flight query
    (lock-guarded), reconnect-and-retry once on torn transport."""

    def __init__(self, host: str, port: int, user: str = "",
                 password: str = "", keyspace: str = "",
                 timeout: float = 10.0):
        self.addr = (host, int(port))
        self.user = user
        self.password = password
        # identifier context: double-quote doubling, NOT the string-
        # literal escaper — stored once so reconnects USE the same name
        self.keyspace = keyspace.replace('"', '""')
        self.timeout = float(timeout)
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._stream = 0
        self._lock = make_lock("cassandra_store._lock")

    # -- framing ----------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise CassandraConnectionError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_frame(self) -> Tuple[int, bytes]:
        head = self._recv_exact(9)
        opcode = head[4]
        (length,) = struct.unpack(">I", head[5:9])
        return opcode, self._recv_exact(length)

    def _send_frame(self, opcode: int, body: bytes):
        self._stream = (self._stream + 1) & 0x7FFF
        self._sock.sendall(
            struct.pack(">BBhBI", 0x04, 0x00, self._stream, opcode,
                        len(body)) + body)

    @staticmethod
    def _string(s: str) -> bytes:
        b = s.encode()
        return struct.pack(">H", len(b)) + b

    @staticmethod
    def _long_string(s: str) -> bytes:
        b = s.encode()
        return struct.pack(">I", len(b)) + b

    # -- startup -----------------------------------------------------------

    def _connect(self):
        self._sock = socket.create_connection(self.addr,
                                              timeout=self.timeout)
        self._sock.settimeout(self.timeout)
        self._buf = b""
        body = struct.pack(">H", 1) + self._string("CQL_VERSION") \
            + self._string("3.0.0")
        self._send_frame(OP_STARTUP, body)
        opcode, payload = self._recv_frame()
        if opcode == OP_AUTHENTICATE:
            token = b"\x00" + self.user.encode() + b"\x00" \
                + self.password.encode()
            self._send_frame(OP_AUTH_RESPONSE,
                             struct.pack(">i", len(token)) + token)
            opcode, payload = self._recv_frame()
            if opcode == OP_ERROR:
                raise CassandraError(self._err_text(payload))
            if opcode != OP_AUTH_SUCCESS:
                raise CassandraError(
                    f"unexpected auth reply opcode {opcode:#x}")
        elif opcode == OP_ERROR:
            raise CassandraError(self._err_text(payload))
        elif opcode != OP_READY:
            raise CassandraError(
                f"unexpected startup reply opcode {opcode:#x}")
        if self.keyspace:
            # the keyspace selection is PER CONNECTION: a reconnect
            # after torn transport must re-issue it or every later
            # statement fails with "no keyspace specified"
            self._query_once(f'USE "{self.keyspace}"')

    @staticmethod
    def _err_text(payload: bytes) -> str:
        (code,) = struct.unpack(">i", payload[:4])
        (n,) = struct.unpack(">H", payload[4:6])
        return (f"cassandra error {code:#06x}: "
                f"{payload[6:6 + n].decode('utf-8', 'replace')}")

    # -- query -------------------------------------------------------------

    def query(self, cql: str):
        with self._lock:
            if self._sock is None:
                self._connect()
                return self._query_once(cql)
            try:
                return self._query_once(cql)
            except (OSError, CassandraConnectionError):
                self.close_nolock()
                self._connect()
                return self._query_once(cql)

    def _query_once(self, cql: str):
        # long string + consistency ONE + empty flags
        body = self._long_string(cql) + struct.pack(">HB", 0x0001, 0x00)
        self._send_frame(OP_QUERY, body)
        opcode, payload = self._recv_frame()
        if opcode == OP_ERROR:
            raise CassandraError(self._err_text(payload))
        if opcode != OP_RESULT:
            raise CassandraError(
                f"unexpected query reply opcode {opcode:#x}")
        (kind,) = struct.unpack(">i", payload[:4])
        if kind != RESULT_ROWS:
            return None
        return self._parse_rows(payload[4:])

    def _parse_rows(self, b: bytes) -> List[tuple]:
        pos = 0
        (flags,) = struct.unpack(">i", b[pos:pos + 4])
        (ncols,) = struct.unpack(">i", b[pos + 4:pos + 8])
        pos += 8
        if flags & META_HAS_MORE_PAGES:
            (n,) = struct.unpack(">i", b[pos:pos + 4])
            pos += 4 + max(0, n)
        if not flags & META_NO_METADATA:
            if flags & META_GLOBAL_TABLES_SPEC:
                for _ in range(2):          # keyspace, table
                    (n,) = struct.unpack(">H", b[pos:pos + 2])
                    pos += 2 + n
            for _ in range(ncols):
                if not flags & META_GLOBAL_TABLES_SPEC:
                    for _ in range(2):
                        (n,) = struct.unpack(">H", b[pos:pos + 2])
                        pos += 2 + n
                (n,) = struct.unpack(">H", b[pos:pos + 2])
                pos += 2 + n                # column name
                (tid,) = struct.unpack(">H", b[pos:pos + 2])
                pos += 2
                if tid == 0x0000:           # custom: string class
                    (n,) = struct.unpack(">H", b[pos:pos + 2])
                    pos += 2 + n
                # primitive types carry no extra option payload
        (nrows,) = struct.unpack(">i", b[pos:pos + 4])
        pos += 4
        out = []
        for _ in range(nrows):
            row = []
            for _ in range(ncols):
                (n,) = struct.unpack(">i", b[pos:pos + 4])
                pos += 4
                if n < 0:
                    row.append(None)
                else:
                    row.append(b[pos:pos + n])
                    pos += n
            out.append(tuple(row))
        return out

    def close_nolock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        with self._lock:
            self.close_nolock()


@register_store
class CassandraStore(FilerStore):
    """`-store cassandra -cassandraAddr host:port [-cassandraUser ..
    -cassandraPassword ..] [-cassandraKeyspace seaweedfs]` — the 7th
    backend, completing the reference's store-family coverage."""

    name = "cassandra"

    def initialize(self, addr: str = "127.0.0.1:9042", user: str = "",
                   password: str = "", keyspace: str = "seaweedfs",
                   timeout: float = 10.0, **options):
        host, _, port = addr.rpartition(":")
        host = host.strip("[]")
        if not host or not port.isdigit():
            raise ValueError(
                f"bad cassandra addr {addr!r}: want host:port")
        # the keyspace must exist before any connection can USE it, so
        # bootstrap with a keyspace-less client first
        boot = CqlClient(host, int(port), user=user, password=password,
                         timeout=timeout)
        ks = keyspace.replace('"', '""')
        boot.query(
            f"CREATE KEYSPACE IF NOT EXISTS \"{ks}\" WITH replication "
            "= {'class': 'SimpleStrategy', 'replication_factor': 1}")
        boot.close()
        self._client = CqlClient(host, int(port), user=user,
                                 password=password, keyspace=keyspace,
                                 timeout=timeout)
        self._known_dirs = set()
        self._client.query(
            "CREATE TABLE IF NOT EXISTS filemeta ("
            "directory text, name text, meta blob, "
            "PRIMARY KEY (directory, name))")

    @staticmethod
    def _split(full_path: str) -> Tuple[str, str]:
        return (posixpath.dirname(full_path) or "/",
                posixpath.basename(full_path))

    def _upsert(self, entry: Entry):
        d, name = self._split(entry.full_path)
        self._client.query(
            "INSERT INTO filemeta (directory,name,meta) VALUES "
            f"('{cql_escape(d)}','{cql_escape(name)}',"
            f"0x{entry.encode().hex()})")
        self._materialize_ancestors(d)

    def _materialize_ancestors(self, d: str):
        """Directory-marker rows for every ancestor of `d` that lacks
        one. The partition-keyed layout can only recurse over
        directories it can SEE (delete_folder_children), so the store
        guarantees its own visibility instead of relying on callers
        going through the filer's ensure_parents — the contract the
        prefix-scanning stores get for free from their key spaces."""
        from .entry import new_dir_entry
        while d != "/" and d not in self._known_dirs:
            parent, name = self._split(d)
            rows = self._client.query(
                "SELECT meta FROM filemeta WHERE "
                f"directory='{cql_escape(parent)}' "
                f"AND name='{cql_escape(name)}'")
            if not rows:
                marker = new_dir_entry(d)
                self._client.query(
                    "INSERT INTO filemeta (directory,name,meta) VALUES "
                    f"('{cql_escape(parent)}','{cql_escape(name)}',"
                    f"0x{marker.encode().hex()})")
            self._known_dirs.add(d)
            d = parent

    def insert_entry(self, entry: Entry) -> None:
        self._upsert(entry)

    def update_entry(self, entry: Entry) -> None:
        self._upsert(entry)  # cassandra INSERT is an upsert

    def find_entry(self, full_path: str) -> Optional[Entry]:
        d, name = self._split(full_path)
        rows = self._client.query(
            "SELECT meta FROM filemeta WHERE "
            f"directory='{cql_escape(d)}' AND name='{cql_escape(name)}'")
        if not rows or rows[0][0] is None:
            return None
        return Entry.decode(full_path, rows[0][0])

    def delete_entry(self, full_path: str) -> None:
        d, name = self._split(full_path)
        self._client.query(
            "DELETE FROM filemeta WHERE "
            f"directory='{cql_escape(d)}' AND name='{cql_escape(name)}'")

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        # recursive walk over MATERIALIZED directory entries (the
        # filer guarantees them), then drop this directory's whole
        # partition — see the module docstring for why a partition key
        # cannot be prefix-scanned like the other stores' key spaces
        start = ""
        while True:
            batch = self.list_directory_entries(base, start, False,
                                                1000)
            for e in batch:
                if e.is_directory:
                    self.delete_folder_children(e.full_path)
            if len(batch) < 1000:
                break
            start = batch[-1].name
        self._client.query(
            f"DELETE FROM filemeta WHERE directory='{cql_escape(base)}'")
        # evict the subtree from the materialization cache: a later
        # insert under a deleted directory must re-create its markers
        prefix = base if base.endswith("/") else base + "/"
        self._known_dirs = {k for k in self._known_dirs
                            if k != base and not k.startswith(prefix)}

    def list_directory_entries(self, dir_path: str, start_file_name: str,
                               inclusive: bool,
                               limit: int) -> List[Entry]:
        d = dir_path.rstrip("/") or "/"
        cond = ""
        if start_file_name:
            op = ">=" if inclusive else ">"
            cond = f" AND name{op}'{cql_escape(start_file_name)}'"
        rows = self._client.query(
            "SELECT name, meta FROM filemeta WHERE "
            f"directory='{cql_escape(d)}'{cond} "
            f"ORDER BY name ASC LIMIT {int(limit)}")
        base = d.rstrip("/")
        return [Entry.decode(f"{base}/{name.decode()}", meta)
                for name, meta in (rows or []) if meta is not None]

    def close(self):
        self._client.close()
