"""MySQL filer store over a from-scratch wire-protocol client (no SDK).

Reference weed/filer2/mysql/mysql_store.go + abstract_sql/
abstract_sql_store.go (database/sql + go-sql-driver): one `filemeta`
table keyed by (dirhash, name) where dirhash is the md5-derived 64-bit
hash of the directory path (reference util.HashStringToLong,
weed/util/bytes.go:53) — listings become an indexed range scan on
(dirhash, name>start).

The client speaks the MySQL client/server protocol over one TCP
connection: handshake v10, mysql_native_password auth (+ auth-switch),
COM_QUERY text protocol with OK/ERR/resultset parsing — enough for the
whole FilerStore contract against MySQL/MariaDB/Percona/Vitess, with
zero dependencies. Values ride as escaped literals (blobs as X'..'
hex), so no prepared-statement round trips.

Layout difference from the reference, on purpose: this filer's
delete_folder_children contract is RECURSIVE (every store here —
memory/sqlite/sharded/redis — prefix-deletes the subtree), so the
delete targets `directory = base OR directory LIKE 'base/%'` instead
of the reference's direct-children-only `directory = ?`.
"""

from __future__ import annotations

import hashlib
import posixpath
import socket
import struct
import threading
from ..util.locks import make_lock
from typing import List, Optional, Tuple

from .entry import Entry
from .filerstore import FilerStore, register_store

# capability flags (mysql_com.h)
_CAP_LONG_PASSWORD = 0x1
_CAP_CONNECT_WITH_DB = 0x8
_CAP_PROTOCOL_41 = 0x200
_CAP_SECURE_CONNECTION = 0x8000
_CAP_PLUGIN_AUTH = 0x80000

# server status flag: sql_mode=NO_BACKSLASH_ESCAPES is active — the
# server treats backslash as a LITERAL inside string literals, so
# backslash-escaping would both corrupt stored names and reopen
# injection through quotes (go-sql-driver tracks the same flag)
SERVER_STATUS_NO_BACKSLASH_ESCAPES = 0x200


class MysqlError(Exception):
    """Server ERR packet — not fixable by reconnecting."""


class MysqlConnectionError(MysqlError):
    """Torn transport — retriable with a reconnect."""


def hash_string_to_long(s: str) -> int:
    """Reference util.HashStringToLong: first 8 md5 bytes, big-endian,
    as a SIGNED 64-bit value (it lands in a BIGINT column)."""
    b = hashlib.md5(s.encode()).digest()
    v = int.from_bytes(b[:8], "big")
    return v - (1 << 64) if v >> 63 else v


def _native_password(password: str, nonce: bytes) -> bytes:
    """mysql_native_password scramble:
    SHA1(pw) XOR SHA1(nonce + SHA1(SHA1(pw)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(nonce + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def escape_string(s: str, no_backslash_escapes: bool = False) -> str:
    """String-literal escaping for the server's CURRENT sql_mode.
    Under NO_BACKSLASH_ESCAPES only quote-doubling is valid (and
    backslashes must stay literal); otherwise the classic backslash
    scheme."""
    if no_backslash_escapes:
        return s.replace("'", "''")
    out = []
    for ch in s:
        if ch in ("'", '"', "\\"):
            out.append("\\" + ch)
        elif ch == "\x00":
            out.append("\\0")
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\r":
            out.append("\\r")
        elif ch == "\x1a":
            out.append("\\Z")
        else:
            out.append(ch)
    return "".join(out)


class MysqlClient:
    """Minimal text-protocol client: one connection, one in-flight
    query (lock-guarded), reconnect-and-retry once on torn transport."""

    def __init__(self, host: str, port: int, user: str, password: str,
                 database: str, timeout: float = 10.0):
        self.addr = (host, int(port))
        self.user = user
        self.password = password
        self.database = database
        self.timeout = float(timeout)
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._seq = 0
        self.status = 0   # server status flags (handshake + OK packets)
        self._lock = make_lock("mysql_store._lock")

    def escape(self, s: str) -> str:
        return escape_string(
            s, bool(self.status & SERVER_STATUS_NO_BACKSLASH_ESCAPES))

    # -- packet framing ---------------------------------------------------

    def _recv_one(self) -> bytes:
        while len(self._buf) < 4:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise MysqlConnectionError("connection closed")
            self._buf += chunk
        size = int.from_bytes(self._buf[:3], "little")
        self._seq = (self._buf[3] + 1) & 0xFF
        while len(self._buf) < 4 + size:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise MysqlConnectionError("connection closed")
            self._buf += chunk
        payload = self._buf[4:4 + size]
        self._buf = self._buf[4 + size:]
        return payload

    def _recv_packet(self) -> bytes:
        """One logical packet: 0xFFFFFF-sized frames continue into the
        next frame (LONGBLOB meta can push a row past 16MB)."""
        payload = self._recv_one()
        if len(payload) < 0xFFFFFF:
            return payload
        out = [payload]
        while len(payload) == 0xFFFFFF:
            payload = self._recv_one()
            out.append(payload)
        return b"".join(out)

    def _send_packet(self, payload: bytes):
        # frames cap at 0xFFFFFF; a payload at exactly the cap needs an
        # empty continuation frame to mark the end
        out = []
        while True:
            frame, payload = payload[:0xFFFFFF], payload[0xFFFFFF:]
            out.append(len(frame).to_bytes(3, "little")
                       + bytes([self._seq]) + frame)
            self._seq = (self._seq + 1) & 0xFF
            if len(frame) < 0xFFFFFF:
                break
        self._sock.sendall(b"".join(out))

    # -- handshake --------------------------------------------------------

    def _connect(self):
        self._sock = socket.create_connection(self.addr,
                                              timeout=self.timeout)
        self._sock.settimeout(self.timeout)
        self._buf = b""
        self._seq = 0
        greeting = self._recv_packet()
        if greeting[:1] == b"\xff":
            raise MysqlError(self._err_text(greeting))
        if greeting[0] != 10:
            raise MysqlError(
                f"unsupported handshake protocol {greeting[0]}")
        pos = 1
        end = greeting.index(b"\x00", pos)          # server version
        pos = end + 1 + 4                           # connection id
        nonce = greeting[pos:pos + 8]
        pos += 8 + 1                                # filler
        caps = int.from_bytes(greeting[pos:pos + 2], "little")
        pos += 2
        plugin = "mysql_native_password"
        if len(greeting) > pos:
            pos += 1                                # charset
            self.status = int.from_bytes(greeting[pos:pos + 2],
                                         "little")
            pos += 2
            caps |= int.from_bytes(greeting[pos:pos + 2],
                                   "little") << 16
            pos += 2
            auth_len = greeting[pos]
            pos += 1 + 10                           # reserved
            if caps & _CAP_SECURE_CONNECTION:
                n = max(13, auth_len - 8)
                nonce += greeting[pos:pos + n].rstrip(b"\x00")
                pos += n
            if caps & _CAP_PLUGIN_AUTH:
                end = greeting.find(b"\x00", pos)
                if end < 0:
                    end = len(greeting)
                plugin = greeting[pos:end].decode()
        nonce = nonce[:20]

        my_caps = (_CAP_LONG_PASSWORD | _CAP_PROTOCOL_41
                   | _CAP_SECURE_CONNECTION | _CAP_PLUGIN_AUTH)
        if self.database:
            my_caps |= _CAP_CONNECT_WITH_DB
        auth = _native_password(self.password, nonce)
        resp = (struct.pack("<IIB", my_caps, 16 << 20, 33)
                + b"\x00" * 23 + self.user.encode() + b"\x00"
                + bytes([len(auth)]) + auth)
        if self.database:
            resp += self.database.encode() + b"\x00"
        resp += b"mysql_native_password\x00"
        self._send_packet(resp)

        pkt = self._recv_packet()
        if pkt[:1] == b"\xfe" and len(pkt) > 1:
            # AuthSwitchRequest: re-scramble with the new nonce
            end = pkt.index(b"\x00", 1)
            switch_plugin = pkt[1:end].decode()
            if switch_plugin != "mysql_native_password":
                raise MysqlError(
                    f"unsupported auth plugin {switch_plugin!r}")
            new_nonce = pkt[end + 1:].rstrip(b"\x00")[:20]
            self._send_packet(_native_password(self.password, new_nonce))
            pkt = self._recv_packet()
        if pkt[:1] == b"\xff":
            raise MysqlError(self._err_text(pkt))
        if pkt[:1] != b"\x00":
            raise MysqlError(f"unexpected auth reply {pkt[:1]!r}")
        self._parse_ok(pkt)

    @staticmethod
    def _err_text(pkt: bytes) -> str:
        code = int.from_bytes(pkt[1:3], "little")
        msg = pkt[3:]
        if msg[:1] == b"#":  # sql-state marker
            msg = msg[6:]
        return f"mysql error {code}: {msg.decode('utf-8', 'replace')}"

    # -- lenenc helpers ---------------------------------------------------

    @staticmethod
    def _lenenc(buf: bytes, pos: int) -> Tuple[Optional[int], int]:
        b = buf[pos]
        if b < 0xFB:
            return b, pos + 1
        if b == 0xFB:
            return None, pos + 1  # NULL
        if b == 0xFC:
            return int.from_bytes(buf[pos + 1:pos + 3], "little"), pos + 3
        if b == 0xFD:
            return int.from_bytes(buf[pos + 1:pos + 4], "little"), pos + 4
        return int.from_bytes(buf[pos + 1:pos + 9], "little"), pos + 9

    # -- query ------------------------------------------------------------

    def query(self, sql: str):
        """Run one statement; returns rows (list of tuples of
        bytes/None) for resultsets, or the affected-row count for
        OK."""
        with self._lock:
            if self._sock is None:
                self._connect()
                return self._query_once(sql)
            try:
                return self._query_once(sql)
            except (OSError, MysqlConnectionError):
                self.close_nolock()
                self._connect()
                return self._query_once(sql)

    def _parse_ok(self, pkt: bytes) -> int:
        """OK packet: affected rows; tracks the server status flags
        (sql_mode changes like NO_BACKSLASH_ESCAPES ride here)."""
        affected, pos = self._lenenc(pkt, 1)
        _, pos = self._lenenc(pkt, pos)  # last insert id
        if pos + 2 <= len(pkt):
            self.status = int.from_bytes(pkt[pos:pos + 2], "little")
        return affected

    def _query_once(self, sql: str):
        self._seq = 0
        self._send_packet(b"\x03" + sql.encode())
        pkt = self._recv_packet()
        if pkt[:1] == b"\xff":
            raise MysqlError(self._err_text(pkt))
        if pkt[:1] == b"\x00":
            return self._parse_ok(pkt)
        ncols, _ = self._lenenc(pkt, 0)
        for _ in range(ncols):
            self._recv_packet()  # column definitions (unused)
        self._eof()
        rows = []
        while True:
            pkt = self._recv_packet()
            if pkt[:1] == b"\xfe" and len(pkt) < 9:
                break
            if pkt[:1] == b"\xff":
                raise MysqlError(self._err_text(pkt))
            row, pos = [], 0
            for _ in range(ncols):
                n, pos = self._lenenc(pkt, pos)
                if n is None:
                    row.append(None)
                else:
                    row.append(pkt[pos:pos + n])
                    pos += n
            rows.append(tuple(row))
        return rows

    def _eof(self):
        pkt = self._recv_packet()
        if not (pkt[:1] == b"\xfe" and len(pkt) < 9):
            raise MysqlError(f"expected EOF, got {pkt[:1]!r}")

    def close_nolock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        with self._lock:
            self.close_nolock()


@register_store
class MysqlStore(FilerStore):
    """`-store mysql -mysqlAddr host:port -mysqlUser .. -mysqlPassword
    .. -mysqlDatabase ..` — the 5th real backend in the store matrix."""

    name = "mysql"

    CREATE = ("CREATE TABLE IF NOT EXISTS filemeta ("
              "dirhash BIGINT, name VARCHAR(1000), directory TEXT, "
              "meta LONGBLOB, PRIMARY KEY (dirhash, name), "
              # recursive deletes predicate on directory; without a
              # prefix index they would full-scan (and row-lock) the
              # whole table
              "KEY directory_prefix (directory(255)))")

    def initialize(self, addr: str = "127.0.0.1:3306", user: str = "root",
                   password: str = "", database: str = "seaweedfs",
                   timeout: float = 10.0, **options):
        host, _, port = addr.rpartition(":")
        host = host.strip("[]")
        if not host or not port.isdigit():
            raise ValueError(f"bad mysql addr {addr!r}: want host:port")
        self._client = MysqlClient(host, int(port), user, password,
                                   database, timeout=timeout)
        self._client.query(self.CREATE)  # fail fast on a bad endpoint

    # -- sql shaping -------------------------------------------------------

    @staticmethod
    def _split(full_path: str) -> Tuple[int, str, str]:
        d = posixpath.dirname(full_path) or "/"
        return hash_string_to_long(d), posixpath.basename(full_path), d

    def _upsert(self, entry: Entry):
        dirhash, name, d = self._split(entry.full_path)
        meta = entry.encode()
        esc = self._client.escape
        self._client.query(
            "INSERT INTO filemeta (dirhash,name,directory,meta) VALUES "
            f"({dirhash},'{esc(name)}',"
            f"'{esc(d)}',X'{meta.hex()}') "
            "ON DUPLICATE KEY UPDATE directory=VALUES(directory),"
            "meta=VALUES(meta)")

    # -- FilerStore --------------------------------------------------------

    def insert_entry(self, entry: Entry) -> None:
        self._upsert(entry)

    def update_entry(self, entry: Entry) -> None:
        # upsert like every other store here (the reference's UPDATE
        # would silently no-op for a missing row)
        self._upsert(entry)

    def find_entry(self, full_path: str) -> Optional[Entry]:
        dirhash, name, d = self._split(full_path)
        esc = self._client.escape
        rows = self._client.query(
            "SELECT meta FROM filemeta WHERE "
            f"dirhash={dirhash} AND name='{esc(name)}' "
            f"AND directory='{esc(d)}'")
        if not rows or rows[0][0] is None:
            return None
        return Entry.decode(full_path, rows[0][0])

    def delete_entry(self, full_path: str) -> None:
        dirhash, name, d = self._split(full_path)
        esc = self._client.escape
        self._client.query(
            "DELETE FROM filemeta WHERE "
            f"dirhash={dirhash} AND name='{esc(name)}' "
            f"AND directory='{esc(d)}'")

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        esc = self._client.escape(base)
        # LIKE-level escaping FIRST (backslash, %, _ are pattern
        # metacharacters), THEN string-literal escaping — a path
        # containing a backslash would otherwise match (and delete)
        # an unrelated subtree
        like_raw = base.rstrip("/")
        like_raw = like_raw.replace("\\", "\\\\") \
            .replace("%", "\\%").replace("_", "\\_")
        like = self._client.escape(like_raw)
        self._client.query(
            "DELETE FROM filemeta WHERE "
            f"directory='{esc}' OR directory LIKE '{like}/%'")

    def list_directory_entries(self, dir_path: str, start_file_name: str,
                               inclusive: bool,
                               limit: int) -> List[Entry]:
        d = dir_path.rstrip("/") or "/"
        dirhash = hash_string_to_long(d)
        op = ">=" if inclusive else ">"
        esc = self._client.escape
        rows = self._client.query(
            "SELECT name, meta FROM filemeta WHERE "
            f"dirhash={dirhash} AND name{op}"
            f"'{esc(start_file_name)}' "
            f"AND directory='{esc(d)}' "
            f"ORDER BY name ASC LIMIT {int(limit)}")
        base = d.rstrip("/")
        return [Entry.decode(f"{base}/{name.decode()}", meta)
                for name, meta in rows if meta is not None]

    def close(self):
        self._client.close()
