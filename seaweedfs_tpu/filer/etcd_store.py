"""etcd filer store — the reference's 7th store-family slot.

Reference weed/filer2/etcd/etcd_store.go: keys are
``<dir>\\x00<name>`` (DIR_FILE_SEPARATOR = 0x00), the value is the
encoded entry, listing is a prefix range over ``<dir>\\x00`` and
recursive delete is a prefix delete.  The reference talks gRPC via
clientv3; etcd serves the identical KV API over its JSON gateway
(``POST /v3/kv/{put,range,deleterange}`` with base64 keys/values,
``/v3/auth/authenticate`` minting a bearer token), which is what this
dependency-free client speaks.

Two deliberate deviations from the reference store, both toward the
contract the rest of this filer relies on:

- listings are ascending (the reference sorts DESCEND and so lists
  directories in reverse name order — observationally different from
  its own other stores);
- DeleteFolderChildren removes the whole subtree (the reference's
  prefix ``<dir>\\x00`` only removes direct children, stranding
  grandchildren keys forever).
"""

from __future__ import annotations

import base64
import http.client
import json
import posixpath
import threading
from ..util.locks import make_lock
from typing import List, Optional

from .entry import Entry
from .filerstore import FilerStore, register_store

DIR_FILE_SEPARATOR = b"\x00"


class EtcdError(Exception):
    pass


def _b64(data: bytes) -> str:
    return base64.b64encode(data).decode("ascii")


def prefix_end(key: bytes) -> bytes:
    """etcd's WithPrefix() range_end: key with its last non-0xff byte
    incremented (trailing 0xff bytes dropped); an all-0xff key scans to
    the end of the keyspace, spelled ``\\x00`` in etcd's range API."""
    out = bytearray(key)
    while out:
        if out[-1] != 0xFF:
            out[-1] += 1
            return bytes(out)
        out.pop()
    return b"\x00"


class EtcdClient:
    """Minimal etcd v3 JSON-gateway client (KV + password auth).

    One persistent HTTP/1.1 connection guarded by a lock (matching the
    single-connection discipline of the other wire stores here);
    reconnects once per call on a dead keep-alive socket.  When a
    user/password is configured, authenticates up front and re-auths
    transparently when the server reports the bearer token invalid
    (etcd tokens expire server-side).
    """

    @classmethod
    def from_addr(cls, addr: str, **kw) -> "EtcdClient":
        """Single owner of the endpoint-spelling convention for every
        etcd consumer (filer store, master sequencer): host:port with
        bracketed-IPv6 tolerance."""
        host, _, port = addr.rpartition(":")
        host = host.strip("[]")  # bracketed IPv6: [::1]:2379
        if not host or not port.isdigit():
            raise ValueError(f"bad etcd addr {addr!r}: want host:port")
        return cls(host, int(port), **kw)

    def __init__(self, host: str, port: int, user: str = "",
                 password: str = "", timeout: float = 10.0,
                 api_prefix: str = "/v3"):
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.timeout = timeout
        self.api_prefix = api_prefix.rstrip("/")
        self._lock = make_lock("etcd_store._lock")
        self._conn: Optional[http.client.HTTPConnection] = None
        self._token = ""

    # -- transport --------------------------------------------------------

    def _request(self, path: str, payload: dict) -> dict:
        body = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        if self._token:
            headers["Authorization"] = self._token
        last_err: Optional[Exception] = None
        for attempt in range(2):
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout)
            try:
                self._conn.request("POST", self.api_prefix + path, body,
                                   headers)
                resp = self._conn.getresponse()
                data = resp.read()
            except (OSError, http.client.HTTPException) as exc:
                # dead keep-alive socket: drop it and retry once
                try:
                    self._conn.close()
                finally:
                    self._conn = None
                last_err = exc
                continue
            try:
                reply = json.loads(data) if data else {}
            except ValueError:
                raise EtcdError(
                    f"etcd {path}: non-JSON reply (HTTP {resp.status})")
            if resp.status != 200:
                msg = reply.get("error") or reply.get("message") \
                    or data.decode("utf-8", "replace")
                raise EtcdError(
                    f"etcd {path}: HTTP {resp.status}: {msg}")
            return reply
        raise EtcdError(f"etcd {self.host}:{self.port} unreachable: "
                        f"{last_err}")

    def _call(self, path: str, payload: dict) -> dict:
        with self._lock:
            try:
                return self._request(path, payload)
            except EtcdError as exc:
                # expired/revoked bearer: re-authenticate once and retry
                if self.user and "invalid auth token" in str(exc):
                    self._token = ""
                    self._authenticate_locked()
                    return self._request(path, payload)
                raise

    def _authenticate_locked(self):
        reply = self._request("/auth/authenticate",
                              {"name": self.user,
                               "password": self.password})
        token = reply.get("token", "")
        if not token:
            raise EtcdError("etcd authenticate: no token in reply")
        self._token = token

    def authenticate(self):
        with self._lock:
            self._authenticate_locked()

    # -- KV ---------------------------------------------------------------

    def put(self, key: bytes, value: bytes) -> None:
        self._call("/kv/put", {"key": _b64(key), "value": _b64(value)})

    def range(self, key: bytes, range_end: bytes = b"",
              limit: int = 0) -> List[tuple]:
        payload = {"key": _b64(key)}
        if range_end:
            payload["range_end"] = _b64(range_end)
        if limit:
            payload["limit"] = str(limit)
        reply = self._call("/kv/range", payload)
        out = []
        for kv in reply.get("kvs") or []:
            out.append((base64.b64decode(kv["key"]),
                        base64.b64decode(kv.get("value", ""))))
        return out

    def delete_range(self, key: bytes, range_end: bytes = b"") -> int:
        payload = {"key": _b64(key)}
        if range_end:
            payload["range_end"] = _b64(range_end)
        reply = self._call("/kv/deleterange", payload)
        return int(reply.get("deleted", 0))

    def put_if(self, key: bytes, expect: Optional[bytes],
               new_value: bytes) -> bool:
        """Single-key compare-and-swap via /kv/txn: put `new_value` iff
        the key's current value is `expect` (None = iff the key does
        not exist, compared on create_revision == 0 per etcd
        convention). Returns whether the txn succeeded. Field names are
        the snake_case protobuf originals, which etcd's JSON gateway
        always accepts."""
        if expect is None:
            compare = {"key": _b64(key), "target": "CREATE",
                       "create_revision": "0"}
        else:
            compare = {"key": _b64(key), "target": "VALUE",
                       "value": _b64(expect)}
        reply = self._call("/kv/txn", {
            "compare": [compare],
            "success": [{"request_put": {"key": _b64(key),
                                         "value": _b64(new_value)}}],
        })
        return bool(reply.get("succeeded"))

    def close(self):
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                finally:
                    self._conn = None


def _entry_key(full_path: str) -> bytes:
    d = posixpath.dirname(full_path) or "/"
    name = posixpath.basename(full_path)
    return d.encode() + DIR_FILE_SEPARATOR + name.encode()


@register_store
class EtcdStore(FilerStore):
    """`-store etcd -etcdAddr host:port [-etcdUser .. -etcdPassword ..]`."""

    name = "etcd"

    def initialize(self, addr: str = "127.0.0.1:2379", user: str = "",
                   password: str = "", timeout: float = 10.0,
                   api_prefix: str = "/v3", **options):
        self._client = EtcdClient.from_addr(addr, user=user,
                                            password=password,
                                            timeout=timeout,
                                            api_prefix=api_prefix)
        if user:
            self._client.authenticate()
        # fail fast on a bad endpoint (empty range on our own keyspace)
        self._client.range(b"/", limit=1)

    # -- FilerStore -------------------------------------------------------

    def insert_entry(self, entry: Entry) -> None:
        self._client.put(_entry_key(entry.full_path), entry.encode())

    def update_entry(self, entry: Entry) -> None:
        # reference etcd UpdateEntry == InsertEntry (upsert)
        self.insert_entry(entry)

    def find_entry(self, full_path: str) -> Optional[Entry]:
        kvs = self._client.range(_entry_key(full_path))
        if not kvs:
            return None
        return Entry.decode(full_path, kvs[0][1])

    def delete_entry(self, full_path: str) -> None:
        self._client.delete_range(_entry_key(full_path))

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        # direct children: "<base>\x00*"
        direct = base.encode() + DIR_FILE_SEPARATOR
        self._client.delete_range(direct, prefix_end(direct))
        # whole subtree: every key whose directory lives under base —
        # "<base>/..." (for base "/" this is the entire keyspace prefix
        # "/", which is exactly the contract)
        subtree = (base.rstrip("/") + "/").encode()
        self._client.delete_range(subtree, prefix_end(subtree))

    def list_directory_entries(self, dir_path: str, start_file_name: str,
                               inclusive: bool,
                               limit: int) -> List[Entry]:
        dir_path = dir_path.rstrip("/") or "/"
        prefix = dir_path.encode() + DIR_FILE_SEPARATOR
        lo = prefix + start_file_name.encode() if start_file_name \
            else prefix
        # +1 covers the excluded startFileName itself landing in range
        kvs = self._client.range(lo, prefix_end(prefix),
                                 limit=limit + 1 if limit else 0)
        base = dir_path.rstrip("/")
        out: List[Entry] = []
        for key, value in kvs:
            name = key[len(prefix):].decode()
            if not name:
                continue
            if name == start_file_name and not inclusive:
                continue
            out.append(Entry.decode(f"{base}/{name}", value))
            if len(out) >= limit > 0:
                break
        return out

    def close(self):
        self._client.close()
