"""Filer metadata layer: path -> entry (attrs + ordered chunk list).

Reference weed/filer2/: Filer core (filer.go), pluggable FilerStore
(filerstore.go:12-30), chunked-file model (filechunks.go), streaming
reads (stream.go), buckets (filer_buckets.go) and background chunk
deletion (filer_deletion.go).
"""

from .entry import Attr, Entry, FileChunk  # noqa: F401
from .filechunks import (  # noqa: F401
    ChunkView,
    VisibleInterval,
    compact_file_chunks,
    etag,
    minus_chunks,
    non_overlapping_visible_intervals,
    total_size,
    view_from_chunks,
)
from .filer import Filer  # noqa: F401
from .filerstore import FilerStore  # noqa: F401
from .cassandra_store import CassandraStore  # noqa: F401
from .etcd_store import EtcdStore  # noqa: F401
from .memory_store import MemoryStore  # noqa: F401
from .mysql_store import MysqlStore  # noqa: F401
from .postgres_store import PostgresStore  # noqa: F401
from .redis_store import RedisStore  # noqa: F401
from .sharded_store import ShardedStore  # noqa: F401
from .sqlite_store import SqliteStore  # noqa: F401
