"""PostgreSQL filer store over a from-scratch wire-protocol client.

Reference weed/filer2/postgres/postgres_store.go + abstract_sql (lib/pq
driver): the same `filemeta` layout as the mysql store — (dirhash,
name) primary key with the md5-derived directory hash — behind the
FilerStore contract.

The client speaks the PostgreSQL frontend/backend protocol 3.0 over
one TCP connection with zero dependencies: startup, authentication
(trust, cleartext, md5, and SCRAM-SHA-256 — the modern default — via
hashlib.pbkdf2_hmac per RFC 5802/7677), and the Simple Query flow
(RowDescription/DataRow/CommandComplete/ReadyForQuery). Values ride
as literals: PostgreSQL defaults to standard_conforming_strings=on,
so string escaping is quote-doubling ONLY (no backslash modes — the
trap the mysql store has to mode-switch around), and bytea goes as
hex ('\\x…'::bytea) both ways. Upserts use ON CONFLICT DO UPDATE.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import posixpath
import socket
import struct
import threading
from ..util.locks import make_lock
from typing import List, Optional, Tuple

from .entry import Entry
from .filerstore import FilerStore, register_store
from .mysql_store import hash_string_to_long


class PostgresError(Exception):
    """Server ErrorResponse — not fixable by reconnecting."""


class PostgresConnectionError(PostgresError):
    """Torn transport — retriable with a reconnect."""


def pg_escape(s: str) -> str:
    """standard_conforming_strings=on: quote-doubling is the whole
    escape story (backslash is an ordinary character)."""
    return s.replace("'", "''")


def scram_client_proof(password: str, salt: bytes, iterations: int,
                       auth_message: bytes) -> Tuple[bytes, bytes]:
    """(ClientProof, ServerSignature) per RFC 5802 with SHA-256."""
    salted = hashlib.pbkdf2_hmac("sha256", password.encode(), salt,
                                 iterations)
    client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
    stored_key = hashlib.sha256(client_key).digest()
    client_sig = hmac.new(stored_key, auth_message,
                          hashlib.sha256).digest()
    proof = bytes(a ^ b for a, b in zip(client_key, client_sig))
    server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
    server_sig = hmac.new(server_key, auth_message,
                          hashlib.sha256).digest()
    return proof, server_sig


class PostgresClient:
    """Minimal Simple-Query client: one connection, one in-flight
    statement (lock-guarded), reconnect-and-retry once on torn
    transport."""

    def __init__(self, host: str, port: int, user: str, password: str,
                 database: str, timeout: float = 10.0):
        self.addr = (host, int(port))
        self.user = user
        self.password = password
        self.database = database
        self.timeout = float(timeout)
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._lock = make_lock("postgres_store._lock")

    # -- framing ----------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise PostgresConnectionError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_msg(self) -> Tuple[bytes, bytes]:
        """(type byte, payload)."""
        head = self._recv_exact(5)
        kind = head[:1]
        length = struct.unpack(">I", head[1:5])[0]
        return kind, self._recv_exact(length - 4)

    def _send_msg(self, kind: bytes, payload: bytes):
        self._sock.sendall(kind + struct.pack(">I", len(payload) + 4)
                           + payload)

    # -- startup + auth ----------------------------------------------------

    def _connect(self):
        self._sock = socket.create_connection(self.addr,
                                              timeout=self.timeout)
        self._sock.settimeout(self.timeout)
        self._buf = b""
        params = (f"user\x00{self.user}\x00database\x00"
                  f"{self.database}\x00\x00").encode()
        startup = struct.pack(">I", 196608) + params  # protocol 3.0
        self._sock.sendall(struct.pack(">I", len(startup) + 4) + startup)
        scram_state = None
        while True:
            kind, payload = self._recv_msg()
            if kind == b"E":
                raise PostgresError(self._err_text(payload))
            if kind == b"R":
                (auth,) = struct.unpack(">I", payload[:4])
                if auth == 0:            # AuthenticationOk
                    continue
                if auth == 3:            # cleartext
                    self._send_msg(b"p", self.password.encode() + b"\x00")
                    continue
                if auth == 5:            # md5(md5(pw+user)+salt)
                    salt = payload[4:8]
                    inner = hashlib.md5(
                        (self.password + self.user).encode()).hexdigest()
                    outer = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._send_msg(b"p", b"md5" + outer.encode()
                                   + b"\x00")
                    continue
                if auth == 10:           # SASL: pick SCRAM-SHA-256
                    mechs = payload[4:].split(b"\x00")
                    if b"SCRAM-SHA-256" not in mechs:
                        raise PostgresError(
                            f"no supported SASL mechanism in {mechs}")
                    nonce = hashlib.sha256(os.urandom(32)) \
                        .hexdigest()[:24]
                    first_bare = f"n={self.user},r={nonce}".encode()
                    scram_state = {"nonce": nonce,
                                   "first_bare": first_bare}
                    initial = b"n,," + first_bare
                    self._send_msg(
                        b"p", b"SCRAM-SHA-256\x00"
                        + struct.pack(">I", len(initial)) + initial)
                    continue
                if auth == 11:           # SASLContinue (server-first)
                    server_first = payload[4:]
                    fields = dict(
                        kv.split(b"=", 1)
                        for kv in server_first.split(b","))
                    full_nonce = fields[b"r"].decode()
                    if not full_nonce.startswith(scram_state["nonce"]):
                        raise PostgresError(
                            "SCRAM nonce mismatch (MITM?)")
                    import base64
                    salt = base64.b64decode(fields[b"s"])
                    iters = int(fields[b"i"])
                    final_no_proof = f"c=biws,r={full_nonce}".encode()
                    auth_msg = (scram_state["first_bare"] + b","
                                + server_first + b"," + final_no_proof)
                    proof, server_sig = scram_client_proof(
                        self.password, salt, iters, auth_msg)
                    scram_state["server_sig"] = server_sig
                    self._send_msg(
                        b"p", final_no_proof + b",p="
                        + base64.b64encode(proof))
                    continue
                if auth == 12:           # SASLFinal: verify the server
                    import base64
                    fields = dict(kv.split(b"=", 1) for kv in
                                  payload[4:].split(b","))
                    if base64.b64decode(fields[b"v"]) != \
                            scram_state["server_sig"]:
                        raise PostgresError(
                            "SCRAM server signature mismatch")
                    continue
                raise PostgresError(f"unsupported auth method {auth}")
            if kind in (b"S", b"K", b"N"):   # params/keydata/notice
                continue
            if kind == b"Z":             # ReadyForQuery
                break
            raise PostgresError(f"unexpected startup message {kind!r}")
        # PIN the two session settings the literal/bytea shaping
        # assumes — a server (or role/database) configured with the
        # legacy values would otherwise turn quote-doubling into an
        # injection hole and hand back escape-format bytea garbage
        self._query_once("SET standard_conforming_strings = on")
        self._query_once("SET bytea_output = hex")

    @staticmethod
    def _err_text(payload: bytes) -> str:
        parts = {}
        for chunk in payload.split(b"\x00"):
            if chunk:
                parts[chr(chunk[0])] = chunk[1:].decode(
                    "utf-8", "replace")
        return (f"postgres error {parts.get('C', '?')}: "
                f"{parts.get('M', '')}")

    # -- simple query ------------------------------------------------------

    def query(self, sql: str):
        with self._lock:
            if self._sock is None:
                self._connect()
                return self._query_once(sql)
            try:
                return self._query_once(sql)
            except (OSError, PostgresConnectionError):
                self.close_nolock()
                self._connect()
                return self._query_once(sql)

    def _query_once(self, sql: str):
        self._send_msg(b"Q", sql.encode() + b"\x00")
        rows: List[tuple] = []
        result = None
        error = None
        while True:
            kind, payload = self._recv_msg()
            if kind == b"T":             # RowDescription (ignored)
                continue
            if kind == b"D":             # DataRow
                (ncols,) = struct.unpack(">H", payload[:2])
                pos, row = 2, []
                for _ in range(ncols):
                    (n,) = struct.unpack(">i", payload[pos:pos + 4])
                    pos += 4
                    if n < 0:
                        row.append(None)
                    else:
                        row.append(payload[pos:pos + n])
                        pos += n
                rows.append(tuple(row))
                continue
            if kind == b"C":             # CommandComplete
                tag = payload.rstrip(b"\x00").split()
                result = int(tag[-1]) if tag and \
                    tag[-1].isdigit() else 0
                continue
            if kind == b"E":
                error = PostgresError(self._err_text(payload))
                continue                 # Z still follows
            if kind in (b"N", b"S"):
                continue
            if kind == b"Z":             # ReadyForQuery: statement done
                if error is not None:
                    raise error
                return rows if rows else result
            raise PostgresError(f"unexpected message {kind!r}")

    def close_nolock(self):
        if self._sock is not None:
            try:
                self._send_msg(b"X", b"")   # Terminate
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        with self._lock:
            self.close_nolock()


@register_store
class PostgresStore(FilerStore):
    """`-store postgres -postgresAddr host:port -postgresUser ..
    -postgresPassword .. -postgresDatabase ..` — the 6th real backend
    in the store matrix."""

    name = "postgres"

    CREATE = ("CREATE TABLE IF NOT EXISTS filemeta ("
              "dirhash BIGINT, name TEXT, directory TEXT, "
              "meta BYTEA, PRIMARY KEY (dirhash, name))")
    CREATE_IDX = ("CREATE INDEX IF NOT EXISTS filemeta_directory "
                  "ON filemeta (directory)")

    def initialize(self, addr: str = "127.0.0.1:5432",
                   user: str = "postgres", password: str = "",
                   database: str = "seaweedfs",
                   timeout: float = 10.0, **options):
        host, _, port = addr.rpartition(":")
        host = host.strip("[]")
        if not host or not port.isdigit():
            raise ValueError(
                f"bad postgres addr {addr!r}: want host:port")
        self._client = PostgresClient(host, int(port), user, password,
                                      database, timeout=timeout)
        self._client.query(self.CREATE)  # fail fast on a bad endpoint
        self._client.query(self.CREATE_IDX)

    @staticmethod
    def _split(full_path: str) -> Tuple[int, str, str]:
        d = posixpath.dirname(full_path) or "/"
        return hash_string_to_long(d), posixpath.basename(full_path), d

    def _upsert(self, entry: Entry):
        dirhash, name, d = self._split(entry.full_path)
        meta = entry.encode()
        self._client.query(
            "INSERT INTO filemeta (dirhash,name,directory,meta) VALUES "
            f"({dirhash},'{pg_escape(name)}','{pg_escape(d)}',"
            f"'\\x{meta.hex()}'::bytea) "
            "ON CONFLICT (dirhash, name) DO UPDATE SET "
            "directory=EXCLUDED.directory, meta=EXCLUDED.meta")

    def insert_entry(self, entry: Entry) -> None:
        self._upsert(entry)

    def update_entry(self, entry: Entry) -> None:
        self._upsert(entry)

    @staticmethod
    def _bytea(v: bytes) -> bytes:
        """DataRow bytea text format: \\x<hex>."""
        if v.startswith(b"\\x"):
            return bytes.fromhex(v[2:].decode())
        return v

    def find_entry(self, full_path: str) -> Optional[Entry]:
        dirhash, name, d = self._split(full_path)
        rows = self._client.query(
            "SELECT meta FROM filemeta WHERE "
            f"dirhash={dirhash} AND name='{pg_escape(name)}' "
            f"AND directory='{pg_escape(d)}'")
        if not isinstance(rows, list) or not rows or rows[0][0] is None:
            return None
        return Entry.decode(full_path, self._bytea(rows[0][0]))

    def delete_entry(self, full_path: str) -> None:
        dirhash, name, d = self._split(full_path)
        self._client.query(
            "DELETE FROM filemeta WHERE "
            f"dirhash={dirhash} AND name='{pg_escape(name)}' "
            f"AND directory='{pg_escape(d)}'")

    def delete_folder_children(self, full_path: str) -> None:
        base = full_path.rstrip("/") or "/"
        # LIKE metacharacters escaped at the pattern level; the literal
        # level is quote-doubling only (standard_conforming_strings)
        like = base.rstrip("/").replace("\\", "\\\\") \
            .replace("%", "\\%").replace("_", "\\_")
        self._client.query(
            "DELETE FROM filemeta WHERE "
            f"directory='{pg_escape(base)}' OR "
            f"directory LIKE '{pg_escape(like)}/%' ESCAPE '\\'")

    def list_directory_entries(self, dir_path: str, start_file_name: str,
                               inclusive: bool,
                               limit: int) -> List[Entry]:
        d = dir_path.rstrip("/") or "/"
        dirhash = hash_string_to_long(d)
        op = ">=" if inclusive else ">"
        rows = self._client.query(
            "SELECT name, meta FROM filemeta WHERE "
            f"dirhash={dirhash} AND name{op}"
            f"'{pg_escape(start_file_name)}' "
            f"AND directory='{pg_escape(d)}' "
            f"ORDER BY name ASC LIMIT {int(limit)}")
        if not isinstance(rows, list):
            return []
        base = d.rstrip("/")
        return [Entry.decode(f"{base}/{name.decode()}",
                             self._bytea(meta))
                for name, meta in rows if meta is not None]

    def close(self):
        self._client.close()
