"""Shared auto-chunking upload: split data, assign fids, upload chunks.

The write half of the reference's autoChunk
(filer_server_handlers_write_autochunk.go) — used by both the filer HTTP
server and the S3 gateway. With cipher=True each chunk is AES-256-GCM
encrypted under a fresh key before it leaves the filer (reference
filer_server_handlers_write_cipher.go); with compress=True text-ish
content is gzipped first (reference autoChunk's IsGzippable path).
Chunk `size` is always the logical plaintext size — the stored blob may
be smaller (gzip) or larger (nonce+tag).
"""

from __future__ import annotations

import hashlib
import time
from typing import List, Optional, Tuple

from ..client import operation
from ..util import encrypt, gzip_data, is_compressible
from .entry import FileChunk


def split_and_upload(master_url: str, data: bytes, filename: str,
                     chunk_size: int, collection: str = "",
                     replication: str = "", ttl: str = "",
                     content_type: str = "application/octet-stream",
                     cipher: bool = False, compress: bool = False,
                     uploaded: Optional[List[FileChunk]] = None,
                     ) -> Tuple[List[FileChunk], str]:
    """Upload `data` as one or more chunks; returns (chunks, md5hex).

    Empty data uploads nothing and returns ([], md5-of-empty): zero-size
    records are tombstones at the volume layer, so empty objects live as
    an entry with no chunks (matching the reference, whose autoChunk loop
    reads zero chunks from an empty body). If the caller passes an
    ``uploaded`` list, every chunk is appended to it the moment its
    upload succeeds, so a caller that catches a mid-stream failure can
    queue the already-landed fids for deletion instead of leaking them.
    """
    now_ns = time.time_ns()
    chunks: List[FileChunk] = [] if uploaded is None else uploaded
    md5 = hashlib.md5()
    if not data:
        return [], md5.hexdigest()
    want_gzip = compress and is_compressible(filename, content_type)
    for i in range(0, len(data), chunk_size):
        piece = data[i:i + chunk_size]
        md5.update(piece)
        blob, is_gzipped, key = piece, False, b""
        if want_gzip and len(piece) > 128:
            gz = gzip_data(piece)
            if len(gz) < len(piece):
                blob, is_gzipped = gz, True
        if cipher:
            blob, key = encrypt(blob)
        a, up = _assign_and_upload(master_url, blob, filename,
                                   content_type, collection,
                                   replication, ttl)
        chunks.append(FileChunk(fid=a["fid"], offset=i, size=len(piece),
                                mtime=now_ns + i, etag=up.get("eTag", ""),
                                cipher_key=key, is_compressed=is_gzipped))
    return chunks, md5.hexdigest()


def _assign_and_upload(master_url: str, blob: bytes, filename: str,
                       content_type: str, collection: str,
                       replication: str, ttl: str, attempts: int = 3):
    """Assign a fid and upload; a volume frozen or unrouted BETWEEN the
    assign and the upload (maintenance: volume.move/balance/tier or an
    ec.encode freeze) re-assigns to a fresh volume instead of failing
    the client's write — maintenance windows must be invisible to
    writers."""
    from ..server.http_util import HttpError
    for attempt in range(attempts):
        a = operation.assign(master_url, collection=collection,
                             replication=replication, ttl=ttl)
        try:
            up = operation.upload(a["url"], a["fid"], blob,
                                  filename=filename,
                                  content_type=content_type, ttl=ttl,
                                  jwt=a.get("auth", ""))
            return a, up
        except HttpError as e:
            # 503 = transport-level (server gone mid-maintenance,
            # connection refused — http_util wraps those); 500 with a
            # freeze/unroute message = write landed on a frozen volume
            retriable = e.status == 503 or (
                e.status == 500 and ("read only" in str(e)
                                     or "not found" in str(e)))
            if not retriable or attempt + 1 == attempts:
                raise
            # a partial-replication failure may have landed the needle
            # on the primary before the fan-out failed: best-effort
            # delete so the retry's fresh fid doesn't strand it
            try:
                from ..server.http_util import http_call
                headers = {"Authorization": f"Bearer {a['auth']}"} \
                    if a.get("auth") else None
                http_call("DELETE", f"http://{a['url']}/{a['fid']}",
                          headers=headers)
            except Exception:  # noqa: BLE001 - cleanup is best-effort
                pass
            # brief pause: the freeze usually reaches the master within
            # a pulse, after which assigns stop routing to that volume
            time.sleep(0.2 * (attempt + 1))
