"""Shared auto-chunking upload: split data, assign fids, upload chunks.

The write half of the reference's autoChunk
(filer_server_handlers_write_autochunk.go) — used by both the filer HTTP
server and the S3 gateway. With cipher=True each chunk is AES-256-GCM
encrypted under a fresh key before it leaves the filer (reference
filer_server_handlers_write_cipher.go); with compress=True text-ish
content is gzipped first (reference autoChunk's IsGzippable path).
Chunk `size` is always the logical plaintext size — the stored blob may
be smaller (gzip) or larger (nonce+tag).
"""

from __future__ import annotations

import hashlib
import time
from typing import List, Optional, Tuple

from ..client import operation
from ..util import encrypt, gzip_data, is_compressible
from .entry import FileChunk
from ..util import config


def split_and_upload(master_url: str, data: bytes, filename: str,
                     chunk_size: int, collection: str = "",
                     replication: str = "", ttl: str = "",
                     content_type: str = "application/octet-stream",
                     cipher: bool = False, compress: bool = False,
                     uploaded: Optional[List[FileChunk]] = None,
                     ) -> Tuple[List[FileChunk], str]:
    """Upload `data` as one or more chunks; returns (chunks, md5hex).

    Empty data uploads nothing and returns ([], md5-of-empty): zero-size
    records are tombstones at the volume layer, so empty objects live as
    an entry with no chunks (matching the reference, whose autoChunk loop
    reads zero chunks from an empty body). If the caller passes an
    ``uploaded`` list, every chunk is appended to it the moment its
    upload succeeds, so a caller that catches a mid-stream failure can
    queue the already-landed fids for deletion instead of leaking them.
    """
    now_ns = time.time_ns()
    chunks: List[FileChunk] = [] if uploaded is None else uploaded
    md5 = hashlib.md5()
    if not data:
        return [], md5.hexdigest()
    want_gzip = compress and is_compressible(filename, content_type)
    for i in range(0, len(data), chunk_size):
        piece = data[i:i + chunk_size]
        md5.update(piece)
        blob, is_gzipped, key = piece, False, b""
        if want_gzip and len(piece) > 128:
            gz = gzip_data(piece)
            if len(gz) < len(piece):
                blob, is_gzipped = gz, True
        if cipher:
            blob, key = encrypt(blob)
        a, up = _assign_and_upload(master_url, blob, filename,
                                   content_type, collection,
                                   replication, ttl)
        chunks.append(FileChunk(fid=a["fid"], offset=i, size=len(piece),
                                mtime=now_ns + i, etag=up.get("eTag", ""),
                                cipher_key=key, is_compressed=is_gzipped))
    return chunks, md5.hexdigest()


def _assign_and_upload(master_url: str, blob: bytes, filename: str,
                       content_type: str, collection: str,
                       replication: str, ttl: str, attempts: int = 6):
    """Assign a fid and upload; a volume frozen, unrouted, or with a
    dead replica BETWEEN the assign and the upload (maintenance:
    volume.move/balance/tier, an ec.encode freeze, or a crashed node
    whose heartbeat hasn't expired yet) re-assigns to a fresh volume
    instead of failing the client's write — maintenance windows and
    node-death windows must be invisible to writers. A fresh assign
    usually lands on an unaffected volume immediately; once the
    master's heartbeat expiry fires it always does."""
    from ..server.http_util import HttpError, http_call
    failed_vids: set = set()
    failed_urls: set = set()
    for attempt in range(attempts):
        if attempt:
            # backoff spanning roughly a heartbeat-expiry window: the
            # master stops routing to a frozen volume within a pulse
            # and prunes a dead node within a few; each failure also
            # blacklists a sick volume or node, so the walk converges
            time.sleep(config.retry_backoff_s(
                min(0.3 * (2 ** (attempt - 1)), 1.5)))
        a = None
        try:
            a = _fresh_assign(master_url, collection, replication, ttl,
                              failed_vids, failed_urls)
            # chunk uploads ride the holder's native write plane when
            # it advertises one (off-fast-path shapes 307 back and the
            # client follows with method+body preserved). A PLANE-only
            # outage must degrade to the healthy Python server, not
            # blacklist the node: retry a['url'] before classifying.
            try:
                up = operation.upload(a.get("fastUrl") or a["url"],
                                      a["fid"], blob,
                                      filename=filename,
                                      content_type=content_type,
                                      ttl=ttl, jwt=a.get("auth", ""))
            except HttpError:
                if not a.get("fastUrl"):
                    raise
                up = operation.upload(a["url"], a["fid"], blob,
                                      filename=filename,
                                      content_type=content_type,
                                      ttl=ttl, jwt=a.get("auth", ""))
            return a, up
        except HttpError as e:
            if a is None:
                # the ASSIGN failed: retriable when the master is mid
                # leader-transition (503) or every volume is briefly
                # frozen/unroutable (406); anything else is config-level
                if e.status not in (503, 406) or \
                        attempt + 1 == attempts:
                    raise
                continue
            # the UPLOAD failed: 503 = transport-level (node gone —
            # http_util wraps connection errors); 500 with a freeze/
            # unroute/replica-death message = this volume can't take
            # the write right now, but another one can
            retriable = e.status == 503 or (
                e.status == 500 and ("read only" in str(e)
                                     or "not found" in str(e)
                                     or "replication failed" in str(e)))
            if not retriable or attempt + 1 == attempts:
                raise
            if e.status == 503:
                # the whole node is unreachable: skip every volume it
                # fronts, not just this one
                failed_urls.add(a["url"])
            failed_vids.add(a["fid"].split(",")[0])
            if "replication failed" in str(e) or e.status == 503:
                # branches where a needle MAY have landed: the primary
                # wrote before the fan-out failed, or the response was
                # lost after a commit (timeout/reset → 503). Best-
                # effort delete with a short timeout so the retry's
                # fresh fid doesn't strand it; against a truly dead
                # node this fails fast (connection refused) or costs
                # at most the 3s cap
                try:
                    headers = {"Authorization": f"Bearer {a['auth']}"} \
                        if a.get("auth") else None
                    http_call("DELETE",
                              f"http://{a['url']}/{a['fid']}",
                              headers=headers, timeout=3)
                except Exception:  # noqa: BLE001 - best-effort
                    pass


def _fresh_assign(master_url: str, collection: str, replication: str,
                  ttl: str, failed_vids: set, failed_urls: set,
                  rolls: int = 6) -> dict:
    """Assign, re-rolling past volumes/nodes that just refused us (the
    master hands out random writable volumes and only unroutes a sick
    one after a pulse/expiry). After ``rolls`` tries the last pick is
    returned anyway — with everything blacklisted, attempting a known-
    sick volume still beats failing without trying."""
    a = None
    for _ in range(rolls):
        a = operation.assign(master_url, collection=collection,
                             replication=replication, ttl=ttl)
        if a["fid"].split(",")[0] not in failed_vids and \
                a["url"] not in failed_urls:
            break
    return a
