"""Shared auto-chunking upload: split data, assign fids, upload chunks.

The write half of the reference's autoChunk
(filer_server_handlers_write_autochunk.go) — used by both the filer HTTP
server and the S3 gateway.
"""

from __future__ import annotations

import hashlib
import time
from typing import List, Tuple

from ..client import operation
from .entry import FileChunk


def split_and_upload(master_url: str, data: bytes, filename: str,
                     chunk_size: int, collection: str = "",
                     replication: str = "", ttl: str = "",
                     content_type: str = "application/octet-stream",
                     ) -> Tuple[List[FileChunk], str]:
    """Upload `data` as one or more chunks; returns (chunks, md5hex)."""
    now_ns = time.time_ns()
    chunks: List[FileChunk] = []
    md5 = hashlib.md5()
    for i in range(0, max(len(data), 1), chunk_size):
        piece = data[i:i + chunk_size]
        if not piece and i > 0:
            break
        md5.update(piece)
        a = operation.assign(master_url, collection=collection,
                             replication=replication, ttl=ttl)
        up = operation.upload(a["url"], a["fid"], piece, filename=filename,
                              content_type=content_type, ttl=ttl,
                              jwt=a.get("auth", ""))
        chunks.append(FileChunk(fid=a["fid"], offset=i, size=len(piece),
                                mtime=now_ns + i, etag=up.get("eTag", "")))
    return chunks, md5.hexdigest()
