"""Remote filer client: the `Filer` surface over the filer metadata API.

The reference's gateways (s3, webdav, mount) talk to the filer process
over the SeaweedFiler gRPC service (weed/pb/filer.proto:10-45,
weed/filer2/filer_client_util.go); this is the same split for the
TPU build — S3ApiServer / WebDavServer / WFS accept either an
in-process `Filer` or this client, so `weed s3 -filer=host:port`
works standalone.
"""

from __future__ import annotations

import posixpath
import time
from typing import List, Optional

from ..server.http_util import HttpError, get_json, post_json
from .entry import Attr, Entry, FileChunk
from .filer import FilerError, NotFoundError


from .entry import entry_from_wire as _entry_from_json
from .entry import entry_to_wire as _entry_to_json


class FilerClient:
    def __init__(self, filer_url: str, buckets_folder: str = "/buckets"):
        self.url = filer_url.rstrip("/")
        if not self.url.startswith("http"):
            self.url = "http://" + self.url
        self.buckets_folder = buckets_folder

    # -- Filer surface ------------------------------------------------------

    def find_entry(self, full_path: str) -> Entry:
        try:
            out = get_json(f"{self.url}/filer/meta/lookup?path="
                           f"{_q(full_path)}")
        except HttpError as e:
            if e.status == 404:
                raise NotFoundError(full_path) from None
            raise
        return _entry_from_json(out["entry"])

    def exists(self, full_path: str) -> bool:
        try:
            self.find_entry(full_path)
            return True
        except NotFoundError:
            return False

    def list_entries(self, dir_path: str, start_file: str = "",
                     inclusive: bool = False,
                     limit: int = 1000) -> List[Entry]:
        out = get_json(
            f"{self.url}/filer/meta/list?path={_q(dir_path)}"
            f"&lastFileName={_q(start_file)}"
            f"&inclusive={'true' if inclusive else 'false'}&limit={limit}")
        return [_entry_from_json(d) for d in out["entries"]]

    def create_entry(self, entry: Entry) -> Entry:
        self._post("create", {"entry": _entry_to_json(entry)})
        return entry

    def update_entry(self, entry: Entry) -> Entry:
        self._post("update", {"entry": _entry_to_json(entry)})
        return entry

    def delete_entry(self, full_path: str, recursive: bool = False,
                     ignore_recursive_error: bool = False):
        self._post("delete", {"path": full_path, "recursive": recursive,
                              "ignoreRecursiveError":
                              ignore_recursive_error})

    def rename_entry(self, old_path: str, new_path: str):
        self._post("rename", {"old": old_path, "new": new_path})

    def mkdir(self, full_path: str):
        """Create a directory entry (parents included, server-side
        mkdir-p); ok if it already exists."""
        from .entry import entry_to_wire, new_dir_entry
        from ..server.http_util import HttpError
        try:
            self._post("create",
                       {"entry": entry_to_wire(new_dir_entry(full_path))})
        except HttpError as e:
            if e.status != 409:     # 409 = already exists
                raise

    def ensure_parents(self, full_path: str):
        import posixpath
        parent = posixpath.dirname(full_path)
        if parent and parent != "/":
            self.mkdir(parent)

    def queue_chunk_deletion(self, chunks: List[FileChunk]):
        self._post("delete_chunks",
                   {"chunks": [c.to_dict() for c in chunks]})

    # -- bucket helpers (reference weed/filer2/filer_buckets.go) ------------

    def create_bucket(self, name: str, collection: str = "",
                      replication: str = "") -> Entry:
        path = f"{self.buckets_folder}/{name}"
        now = time.time()
        attr = Attr(mtime=now, crtime=now, collection=collection or name,
                    replication=replication)
        attr.set_directory()
        return self.create_entry(Entry(full_path=path, attr=attr))

    def list_buckets(self) -> List[Entry]:
        try:
            return [e for e in self.list_entries(self.buckets_folder,
                                                 limit=10000)
                    if e.is_directory]
        except (NotFoundError, HttpError):
            return []

    def delete_bucket(self, name: str):
        self.delete_entry(f"{self.buckets_folder}/{name}", recursive=True,
                          ignore_recursive_error=True)

    # -- internals ----------------------------------------------------------

    def _post(self, op: str, body: dict):
        try:
            post_json(f"{self.url}/filer/meta/{op}", body)
        except HttpError as e:
            if e.status == 404:
                raise NotFoundError(str(e)) from None
            if e.status == 409:
                raise FilerError(str(e)) from None
            raise


def _q(s: str) -> str:
    import urllib.parse
    return urllib.parse.quote(s, safe="")
