"""Redis filer store over a from-scratch RESP client (no SDK).

Reference weed/filer2/redis/universal_redis_store.go (go-redis client):
entry bytes live at key = full path; each directory keeps a
lexicographic sorted set of child names at key = "<dir>\\x00children"
(score 0, so ZRANGEBYLEX gives ordered, cursorable listings — the same
layout the reference uses with its DIR_LIST_MARKER suffix).

The client speaks RESP2 over one TCP connection (SET/GET/MGET/DEL/
ZADD/ZREM/ZRANGEBYLEX/SCAN/PING/AUTH/SELECT), enough for the whole
FilerStore contract against any Redis-protocol server (Redis, KeyDB,
Valkey, DragonflyDB).
"""

from __future__ import annotations

import posixpath
import socket
import threading
from ..util.locks import make_lock
from typing import List, Optional

from .entry import Entry
from .filerstore import FilerStore, register_store

_CHILDREN_SUFFIX = "\x00children"


class RedisError(Exception):
    """A server error reply (-ERR/-OOM/...) — NOT retriable by
    reconnecting."""


class RedisConnectionError(RedisError):
    """Torn or half-closed connection — retriable with a reconnect."""


class RespClient:
    """Minimal RESP2 client: one connection, one in-flight command
    (guarded by a lock — the filer store serializes per call)."""

    def __init__(self, host: str, port: int, password: str = "",
                 db: int = 0, timeout: float = 10.0):
        self.addr = (host, int(port))
        self.password = password
        self.db = int(db)
        self.timeout = float(timeout)
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._lock = make_lock("redis_store._lock")

    # -- transport --------------------------------------------------------

    def _connect(self):
        self._sock = socket.create_connection(self.addr,
                                              timeout=self.timeout)
        self._sock.settimeout(self.timeout)
        self._buf = b""
        if self.password:
            self._exec("AUTH", self.password)
        if self.db:
            self._exec("SELECT", str(self.db))

    def close(self):
        with self._lock:
            self.close_nolock()

    def command(self, *args):
        """Run one command; reconnect-and-retry once on a torn
        connection (server restart, idle timeout)."""
        with self._lock:
            if self._sock is None:
                self._connect()
                return self._exec(*args)
            try:
                return self._exec(*args)
            except (OSError, RedisConnectionError):
                # only transport failures reconnect-and-retry: a server
                # error reply (-ERR/-OOM/-NOAUTH) came over a healthy
                # connection and can never be fixed by replaying
                self.close_nolock()
                self._connect()
                return self._exec(*args)

    def close_nolock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def transaction(self, *cmds):
        """MULTI/EXEC the given command tuples atomically (one
        pipelined write, one EXEC reply). Same reconnect policy as
        command(); any server error mid-transaction poisons the reply
        stream (unread QUEUED/EXEC replies), so the connection is
        dropped before the error propagates."""
        with self._lock:
            try:
                if self._sock is None:
                    self._connect()
                    return self._exec_multi(cmds)
                try:
                    return self._exec_multi(cmds)
                except (OSError, RedisConnectionError):
                    self.close_nolock()
                    self._connect()
                    return self._exec_multi(cmds)
            except (OSError, RedisError):
                self.close_nolock()
                raise

    def _exec_multi(self, cmds):
        wire = [self._encode(("MULTI",))]
        wire += [self._encode(c) for c in cmds]
        wire.append(self._encode(("EXEC",)))
        self._sock.sendall(b"".join(wire))
        self._read_reply()               # +OK for MULTI
        for _ in cmds:
            self._read_reply()           # +QUEUED per command
        return self._read_reply()        # EXEC: array of results

    @staticmethod
    def _encode(args) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, (bytes, bytearray)) else \
                str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        return b"".join(out)

    def _exec(self, *args):
        self._sock.sendall(self._encode(args))
        return self._read_reply()

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisConnectionError("connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisConnectionError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n + 2:]
        return out

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RedisError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            return self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RedisError(f"bad reply type {line[:20]!r}")


def _children_key(dir_path: str) -> str:
    return (dir_path.rstrip("/") or "/") + _CHILDREN_SUFFIX


@register_store
class RedisStore(FilerStore):
    """`-store redis -redisAddr host:port [-redisPassword ..]
    [-redisDb N]`."""

    name = "redis"

    def initialize(self, addr: str = "127.0.0.1:6379", password: str = "",
                   db: int = 0, timeout: float = 10.0, **options):
        host, _, port = addr.rpartition(":")
        host = host.strip("[]")  # bracketed IPv6: [::1]:6379
        if not host or not port.isdigit():
            raise ValueError(f"bad redis addr {addr!r}: want host:port")
        self._client = RespClient(host, int(port), password=password,
                                  db=db, timeout=timeout)
        self._client.command("PING")  # fail fast on a bad endpoint

    # -- FilerStore -------------------------------------------------------

    def insert_entry(self, entry: Entry) -> None:
        # MULTI/EXEC: the entry and its directory-index membership must
        # land together — a crash between them would leave an entry that
        # GETs but never LISTs (or vice versa)
        self._client.transaction(
            ("SET", entry.full_path, entry.encode()),
            ("ZADD", _children_key(entry.dir_name), "0", entry.name))

    def update_entry(self, entry: Entry) -> None:
        # full upsert like every other store (and the reference's redis
        # UpdateEntry = InsertEntry): Filer.update_entry doesn't require
        # a prior insert, and a SET without the ZADD would mint an entry
        # that GETs but never LISTs
        self.insert_entry(entry)

    def find_entry(self, full_path: str) -> Optional[Entry]:
        data = self._client.command("GET", full_path)
        if data is None:
            return None
        return Entry.decode(full_path, data)

    def delete_entry(self, full_path: str) -> None:
        d = posixpath.dirname(full_path) or "/"
        self._client.transaction(
            ("DEL", full_path),
            ("ZREM", _children_key(d), posixpath.basename(full_path)))

    @staticmethod
    def _glob_escape(s: str) -> str:
        out = []
        for ch in s:
            if ch in "*?[]\\":
                out.append("\\" + ch)
            else:
                out.append(ch)
        return "".join(out)

    def delete_folder_children(self, full_path: str) -> None:
        """Recursive prefix delete (the contract the filer relies on;
        sqlite/memory stores do the same with a LIKE/startswith). A
        child-set walk can't see subtrees whose intermediate directory
        entries were never materialized, so this scans the key space by
        prefix — entry keys AND per-directory children sets under the
        path both match '<base>/*'."""
        base = full_path.rstrip("/") or "/"
        pattern = self._glob_escape(base.rstrip("/")) + "/*"
        cursor = "0"
        while True:
            reply = self._client.command("SCAN", cursor, "MATCH",
                                         pattern, "COUNT", "1000")
            cursor = reply[0].decode() if isinstance(reply[0], bytes) \
                else str(reply[0])
            keys = reply[1] or []
            if keys:
                self._client.command("DEL", *keys)
            if cursor == "0":
                break
        self._client.command("DEL", _children_key(base))

    def list_directory_entries(self, dir_path: str, start_file_name: str,
                               inclusive: bool,
                               limit: int) -> List[Entry]:
        dir_path = dir_path.rstrip("/") or "/"
        if start_file_name:
            lo = ("[" if inclusive else "(") + start_file_name
        else:
            lo = "-"
        names = self._client.command(
            "ZRANGEBYLEX", _children_key(dir_path), lo, "+",
            "LIMIT", "0", str(limit)) or []
        if not names:
            return []
        base = dir_path.rstrip("/")
        paths = [f"{base}/" +
                 (raw.decode() if isinstance(raw, bytes) else raw)
                 for raw in names]
        # one MGET round trip for the whole page, not one GET per child
        values = self._client.command("MGET", *paths) or []
        return [Entry.decode(p, v)
                for p, v in zip(paths, values) if v is not None]

    def close(self):
        self._client.close()
