"""Metadata event log buffer.

Reference weed/queue/log_buffer.go:20-200 + weed/filer2/filer_notify.go:
every entry mutation becomes an event appended to an in-memory buffer
that is flushed on an interval; subscribers replay from a timestamp and
then follow live events (ListenForEvents / `weed watch`).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, List, Optional, Tuple


class LogBuffer:
    """Time-ordered event buffer with bounded memory and flush callback."""

    def __init__(self, flush_interval: float = 60.0,
                 flush_fn: Optional[Callable[[List[dict]], None]] = None,
                 max_events: int = 100_000):
        self._events: List[Tuple[float, dict]] = []
        self._lock = threading.Condition()
        self._flush_fn = flush_fn
        self._flush_interval = flush_interval
        self._max_events = max_events
        self._closed = False

    def append(self, event: dict, ts: Optional[float] = None):
        ts = time.time() if ts is None else ts
        with self._lock:
            self._events.append((ts, event))
            if len(self._events) > self._max_events:
                self._flush_locked()
            self._lock.notify_all()

    def _flush_locked(self):
        if self._flush_fn and self._events:
            batch = [e for _, e in self._events]
            self._flush_fn(batch)
        # keep a tail for late subscribers even after flushing
        self._events = self._events[-1000:]

    def flush(self):
        with self._lock:
            self._flush_locked()

    @staticmethod
    def _take_since(events, ts: float, limit: int):
        """Newer-than-ts slice, never splitting a same-timestamp run at
        the limit: subscribers resume with a strict `> ts` filter, so a
        run cut mid-way would lose its tail forever."""
        got = [(t, e) for t, e in events if t > ts]
        if len(got) > limit:
            cut = limit
            last_ts = got[cut - 1][0]
            while cut < len(got) and got[cut][0] == last_ts:
                cut += 1
            got = got[:cut]
        return got

    def read_since(self, ts: float, limit: int = 1024) -> List[Tuple[float, dict]]:
        with self._lock:
            return self._take_since(self._events, ts, limit)

    def wait_since(self, ts: float, timeout: float = 10.0,
                   limit: int = 1024) -> List[Tuple[float, dict]]:
        """Blocking read: return events newer than ts, waiting up to
        timeout for one to arrive (long-poll analog of the reference's
        server-side stream loop)."""
        deadline = time.time() + timeout
        with self._lock:
            while not self._closed:
                got = self._take_since(self._events, ts, limit)
                if got:
                    return got
                remaining = deadline - time.time()
                if remaining <= 0:
                    return []
                self._lock.wait(remaining)
        return []

    def close(self):
        with self._lock:
            self._closed = True
            self._lock.notify_all()


def event_notification(old, new, delete_chunks: bool) -> dict:
    """Build the EventNotification payload
    (reference filer_pb.EventNotification, filer_notify.go:16-60).
    Entries go out in full wire shape so a replication sink can recreate
    them faithfully (mime, mode, chunks, ...)."""

    def enc(e):
        if e is None:
            return None
        from .entry import entry_to_wire
        d = entry_to_wire(e)
        # kept for pre-wire consumers of the event stream
        d["path"] = e.full_path
        d["isDirectory"] = e.is_directory
        return d

    return {
        "oldEntry": enc(old),
        "newEntry": enc(new),
        "deleteChunks": delete_chunks,
        "tsNs": time.time_ns(),
    }


def encode_event_line(event: dict) -> bytes:
    return json.dumps(event, separators=(",", ":")).encode() + b"\n"
