"""Overlapping-write resolution for chunked files.

Reference weed/filer2/filechunks.go: chunks written at overlapping offsets
are resolved by mtime (newer wins) into non-overlapping VisibleIntervals
(NonOverlappingVisibleIntervals filechunks.go:190), from which a read
range is planned as ChunkViews (ViewFromChunks filechunks.go:93).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, replace
from typing import List, Set, Tuple

from .entry import FileChunk


def total_size(chunks: List[FileChunk]) -> int:
    size = 0
    for c in chunks:
        size = max(size, c.offset + c.size)
    return size


def etag(chunks: List[FileChunk]) -> str:
    """ETag(chunks): single chunk -> its etag; else md5 over chunk etags
    (reference filechunks.go:32-44)."""
    if len(chunks) == 1:
        return chunks[0].etag
    h = hashlib.md5()
    for c in chunks:
        h.update(c.etag.encode())
    return h.hexdigest()


@dataclass(frozen=True)
class VisibleInterval:
    """A [start, stop) byte range of the logical file served by one chunk.
    chunk_offset is where `start` falls inside that chunk's data."""

    start: int
    stop: int
    fid: str
    mtime: int
    chunk_offset: int = 0
    is_full_chunk: bool = True
    cipher_key: bytes = b""
    is_compressed: bool = False


def non_overlapping_visible_intervals(
        chunks: List[FileChunk]) -> List[VisibleInterval]:
    """Overlay chunks in mtime order; later writes clip earlier ones
    (reference MergeIntoVisibles / NonOverlappingVisibleIntervals
    filechunks.go:147-208)."""
    visibles: List[VisibleInterval] = []
    for c in sorted(chunks, key=lambda c: (c.mtime, c.fid)):
        new = VisibleInterval(start=c.offset, stop=c.offset + c.size,
                              fid=c.fid, mtime=c.mtime, chunk_offset=0,
                              is_full_chunk=True,
                              cipher_key=c.cipher_key,
                              is_compressed=c.is_compressed)
        out: List[VisibleInterval] = []
        for v in visibles:
            if v.stop <= new.start or v.start >= new.stop:
                out.append(v)
                continue
            if v.start < new.start:  # head survives
                out.append(replace(v, stop=new.start, is_full_chunk=False))
            if v.stop > new.stop:    # tail survives, shifted into the chunk
                out.append(replace(
                    v, start=new.stop,
                    chunk_offset=v.chunk_offset + (new.stop - v.start),
                    is_full_chunk=False))
        out.append(new)
        visibles = sorted(out, key=lambda v: v.start)
    return visibles


@dataclass(frozen=True)
class ChunkView:
    """One fetch needed to serve part of a read range
    (reference filechunks.go:84-91)."""

    fid: str
    offset: int          # offset inside the chunk's stored data
    size: int
    logical_offset: int  # offset in the file
    is_full_chunk: bool = False
    cipher_key: bytes = b""
    is_compressed: bool = False


def view_from_visible_intervals(visibles: List[VisibleInterval],
                                offset: int, size: int) -> List[ChunkView]:
    if size < 0:  # whole file
        size = max((v.stop for v in visibles), default=0) - offset
    stop = offset + size
    views: List[ChunkView] = []
    for v in visibles:
        if v.start >= stop or v.stop <= offset:
            continue
        lo = max(offset, v.start)
        hi = min(stop, v.stop)
        full = v.is_full_chunk and lo == v.start and hi == v.stop
        views.append(ChunkView(
            fid=v.fid, offset=v.chunk_offset + (lo - v.start),
            size=hi - lo, logical_offset=lo, is_full_chunk=full,
            cipher_key=v.cipher_key, is_compressed=v.is_compressed))
    return views


def view_from_chunks(chunks: List[FileChunk], offset: int,
                     size: int) -> List[ChunkView]:
    return view_from_visible_intervals(
        non_overlapping_visible_intervals(chunks), offset, size)


def compact_file_chunks(
        chunks: List[FileChunk]) -> Tuple[List[FileChunk], List[FileChunk]]:
    """Split chunks into (still visible, fully shadowed garbage)
    (reference CompactFileChunks filechunks.go:46-62)."""
    visible_fids: Set[str] = {
        v.fid for v in non_overlapping_visible_intervals(chunks)}
    compacted = [c for c in chunks if c.fid in visible_fids]
    garbage = [c for c in chunks if c.fid not in visible_fids]
    return compacted, garbage


def minus_chunks(before: List[FileChunk],
                 after: List[FileChunk]) -> List[FileChunk]:
    """Chunks present in `before` but not in `after`
    (reference MinusChunks filechunks.go:64-77)."""
    keep = {(c.fid, c.offset, c.size) for c in after}
    return [c for c in before if (c.fid, c.offset, c.size) not in keep]
