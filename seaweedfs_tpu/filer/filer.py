"""Filer core: directory tree over a FilerStore.

Reference weed/filer2/filer.go:28-53 — CreateEntry ensures ancestor
directories, DeleteEntryMetaAndData recurses and queues chunk deletion
(filer_delete_entry.go, filer_deletion.go), bucket dirs
(filer_buckets.go), LRU directory cache, and a notify hook feeding the
metadata event log (filer_notify.go).
"""

from __future__ import annotations

import posixpath
import threading
from ..util.locks import make_rlock
import time
from collections import OrderedDict
from typing import Callable, List, Optional

from .entry import Attr, Entry, FileChunk, new_dir_entry
from .filerstore import FilerStore


class FilerError(Exception):
    pass


class NotFoundError(FilerError):
    pass


class Filer:
    def __init__(self, store: FilerStore,
                 dir_cache_size: int = 1024,
                 buckets_folder: str = "/buckets"):
        self.store = store
        self.buckets_folder = buckets_folder
        self._dir_cache: "OrderedDict[str, Entry]" = OrderedDict()
        self._dir_cache_size = dir_cache_size
        self._lock = make_rlock("filer._lock")
        # notify(old_entry | None, new_entry | None, delete_chunks: bool)
        self.notify_fns: List[Callable] = []
        # fids queued for deletion on the volume servers
        self._deletion_queue: List[str] = []

    # -- notifications ------------------------------------------------------

    def on_update(self, fn: Callable):
        self.notify_fns.append(fn)

    def _notify(self, old: Optional[Entry], new: Optional[Entry],
                delete_chunks: bool = False):
        for fn in self.notify_fns:
            fn(old, new, delete_chunks)

    # -- directory cache ----------------------------------------------------

    def _cached_dir(self, path: str) -> Optional[Entry]:
        with self._lock:
            e = self._dir_cache.get(path)
            if e is not None:
                self._dir_cache.move_to_end(path)
            return e

    def _cache_dir(self, entry: Entry):
        with self._lock:
            self._dir_cache[entry.full_path] = entry
            self._dir_cache.move_to_end(entry.full_path)
            while len(self._dir_cache) > self._dir_cache_size:
                self._dir_cache.popitem(last=False)

    def _uncache_dir(self, path: str):
        with self._lock:
            self._dir_cache.pop(path, None)

    # -- core operations ----------------------------------------------------

    def ensure_parents(self, full_path: str):
        """Create missing ancestor directories (reference filer.go
        CreateEntry's mkdir loop)."""
        parent = posixpath.dirname(full_path) or "/"
        if parent == "/":
            return
        if self._cached_dir(parent) is not None:
            return
        existing = self.store.find_entry(parent)
        if existing is not None:
            if not existing.is_directory:
                raise FilerError(f"{parent} is a file, not a directory")
            self._cache_dir(existing)
            return
        self.ensure_parents(parent)
        d = new_dir_entry(parent)
        self.store.insert_entry(d)
        self._cache_dir(d)
        self._notify(None, d)

    def create_entry(self, entry: Entry) -> Entry:
        if entry.full_path != "/" and entry.full_path.endswith("/"):
            entry.full_path = entry.full_path.rstrip("/")
        self.ensure_parents(entry.full_path)
        old = self.store.find_entry(entry.full_path)
        if old is not None and old.is_directory and not entry.is_directory:
            raise FilerError(f"{entry.full_path} is a directory")
        self.store.insert_entry(entry)
        if entry.is_directory:
            self._cache_dir(entry)
        self._notify(old, entry,
                     delete_chunks=old is not None and not old.is_directory)
        if old is not None and not old.is_directory:
            from .filechunks import minus_chunks
            self.queue_chunk_deletion(minus_chunks(old.chunks, entry.chunks))
        return entry

    def update_entry(self, entry: Entry) -> Entry:
        old = self.store.find_entry(entry.full_path)
        self.store.update_entry(entry)
        self._notify(old, entry)
        return entry

    def find_entry(self, full_path: str) -> Entry:
        if full_path == "/":
            root = new_dir_entry("/")
            root.attr.mode = 0o40777
            return root
        e = self.store.find_entry(full_path.rstrip("/"))
        if e is None:
            raise NotFoundError(full_path)
        return e

    def exists(self, full_path: str) -> bool:
        try:
            self.find_entry(full_path)
            return True
        except NotFoundError:
            return False

    def list_entries(self, dir_path: str, start_file: str = "",
                     inclusive: bool = False,
                     limit: int = 1024) -> List[Entry]:
        return self.store.list_directory_entries(
            dir_path, start_file, inclusive, limit)

    def delete_entry(self, full_path: str, recursive: bool = False,
                     ignore_recursive_error: bool = False,
                     delete_chunks: bool = True) -> None:
        """Reference filer_delete_entry.go:15-83. ``delete_chunks=False``
        removes metadata only (reference ?skipChunkDeletion — used when
        the chunks are shared or reclaimed elsewhere)."""
        entry = self.find_entry(full_path)
        if entry.is_directory:
            self._delete_dir(entry, recursive, ignore_recursive_error,
                             delete_chunks)
        elif delete_chunks:
            self.queue_chunk_deletion(entry.chunks)
        self.store.delete_entry(entry.full_path)
        self._uncache_dir(entry.full_path)
        self._notify(entry, None, delete_chunks=delete_chunks)

    def _delete_dir(self, entry: Entry, recursive: bool,
                    ignore_error: bool, delete_chunks: bool = True):
        children = self.list_entries(entry.full_path, limit=1 << 30)
        if children and not recursive:
            raise FilerError(f"{entry.full_path}: folder not empty")
        for child in children:
            try:
                if child.is_directory:
                    self._delete_dir(child, recursive, ignore_error,
                                     delete_chunks)
                elif delete_chunks:
                    self.queue_chunk_deletion(child.chunks)
                self.store.delete_entry(child.full_path)
                self._uncache_dir(child.full_path)
                self._notify(child, None, delete_chunks=delete_chunks)
            except FilerError:
                if not ignore_error:
                    raise

    def rename_entry(self, old_path: str, new_path: str) -> Entry:
        """Atomic-in-process rename (reference AtomicRenameEntry gRPC,
        filer_grpc_server_rename.go) — moves subtree for directories."""
        old_path = old_path.rstrip("/") or "/"
        new_path = new_path.rstrip("/") or "/"
        if new_path == old_path:
            return self.find_entry(old_path)
        if new_path.startswith(old_path + "/"):
            raise FilerError(
                f"cannot move {old_path} into its own subtree {new_path}")
        entry = self.find_entry(old_path)
        self.ensure_parents(new_path)
        dest = self.store.find_entry(new_path)
        if dest is not None:
            if dest.is_directory:
                raise FilerError(f"{new_path} is an existing directory")
            # replaced destination: reclaim its chunks like create_entry
            self.queue_chunk_deletion(dest.chunks)
        if entry.is_directory:
            self._rename_tree(entry, old_path, new_path)
        else:
            moved = Entry(full_path=new_path, attr=entry.attr,
                          chunks=entry.chunks, extended=entry.extended)
            self.store.insert_entry(moved)
            self.store.delete_entry(old_path)
            self._notify(entry, moved)
        return self.find_entry(new_path)

    def _rename_tree(self, entry: Entry, old_root: str, new_root: str):
        # snapshot children before inserting the moved copy, so a listing
        # can never see (and recurse into) the destination subtree
        children = self.list_entries(entry.full_path, limit=1 << 30) \
            if entry.is_directory else []
        new_path = new_root + entry.full_path[len(old_root):]
        moved = Entry(full_path=new_path, attr=entry.attr,
                      chunks=entry.chunks, extended=entry.extended)
        self.store.insert_entry(moved)
        for child in children:
            self._rename_tree(child, old_root, new_root)
        self.store.delete_entry(entry.full_path)
        self._uncache_dir(entry.full_path)
        self._notify(entry, moved)

    # -- chunk deletion queue (reference filer_deletion.go) -----------------

    def queue_chunk_deletion(self, chunks: List[FileChunk]):
        with self._lock:
            self._deletion_queue.extend(c.fid for c in chunks)

    def drain_deletion_queue(self) -> List[str]:
        with self._lock:
            fids, self._deletion_queue = self._deletion_queue, []
            return fids

    # -- buckets (reference filer_buckets.go) -------------------------------

    def create_bucket(self, name: str, collection: str = "",
                      replication: str = "") -> Entry:
        path = f"{self.buckets_folder}/{name}"
        now = time.time()
        attr = Attr(mtime=now, crtime=now, mode=0o777,
                    collection=collection or name, replication=replication)
        attr.set_directory()
        return self.create_entry(Entry(full_path=path, attr=attr))

    def list_buckets(self) -> List[Entry]:
        try:
            return [e for e in self.list_entries(self.buckets_folder,
                                                 limit=1 << 20)
                    if e.is_directory]
        except NotFoundError:
            return []

    def delete_bucket(self, name: str):
        self.delete_entry(f"{self.buckets_folder}/{name}", recursive=True,
                          ignore_recursive_error=True)
