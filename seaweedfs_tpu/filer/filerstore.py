"""FilerStore plugin interface.

Reference weed/filer2/filerstore.go:12-30 — Insert/Update/Find/Delete/
DeleteFolderChildren/ListDirectoryEntries (+ transactions, no-ops here
for the embedded stores). Stores register into STORES by name so the
filer config can pick one (reference filer.toml sections).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Type

from .entry import Entry


class FilerStore:
    name = "abstract"

    def initialize(self, **options):
        pass

    def insert_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def update_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def find_entry(self, full_path: str) -> Optional[Entry]:
        raise NotImplementedError

    def delete_entry(self, full_path: str) -> None:
        raise NotImplementedError

    def delete_folder_children(self, full_path: str) -> None:
        raise NotImplementedError

    def list_directory_entries(self, dir_path: str, start_file_name: str,
                               inclusive: bool,
                               limit: int) -> List[Entry]:
        raise NotImplementedError

    # transactions — embedded stores are synchronous; kept for interface
    # parity with reference BeginTransaction/CommitTransaction/Rollback
    def begin_transaction(self):
        pass

    def commit_transaction(self):
        pass

    def rollback_transaction(self):
        pass

    def close(self):
        pass


STORES: Dict[str, Type[FilerStore]] = {}


def register_store(cls: Type[FilerStore]) -> Type[FilerStore]:
    STORES[cls.name] = cls
    return cls


def make_store(name: str, **options) -> FilerStore:
    cls = STORES.get(name)
    if cls is None:
        raise ValueError(f"unknown filer store {name!r}; "
                         f"have {sorted(STORES)}")
    store = cls()
    store.initialize(**options)
    return store
