"""Filer entry model.

Reference weed/filer2/entry.py analog: Entry = full path + Attr +
ordered []FileChunk (entry.go:14-42), serialized for storage
(entry_codec.go — we use JSON instead of protobuf).
"""

from __future__ import annotations

import json
import posixpath
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class FileChunk:
    """One stored chunk of a file (reference filer.proto FileChunk)."""

    fid: str            # "<vid>,<key><cookie>" on a volume server
    offset: int         # logical offset within the file
    size: int
    mtime: int = 0      # ns timestamp; newer chunks overlay older ones
    etag: str = ""
    cipher_key: bytes = b""
    is_compressed: bool = False

    def to_dict(self) -> dict:
        d = {"fid": self.fid, "offset": self.offset, "size": self.size,
             "mtime": self.mtime}
        if self.etag:
            d["etag"] = self.etag
        if self.cipher_key:
            d["cipherKey"] = self.cipher_key.hex()
        if self.is_compressed:
            d["isCompressed"] = True
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FileChunk":
        return cls(fid=d["fid"], offset=d["offset"], size=d["size"],
                   mtime=d.get("mtime", 0), etag=d.get("etag", ""),
                   cipher_key=bytes.fromhex(d.get("cipherKey", "")),
                   is_compressed=d.get("isCompressed", False))


@dataclass
class Attr:
    """Entry attributes (reference entry.go:14-28)."""

    mtime: float = 0.0
    crtime: float = 0.0
    mode: int = 0o660
    uid: int = 0
    gid: int = 0
    mime: str = ""
    replication: str = ""
    collection: str = ""
    ttl_sec: int = 0
    user_name: str = ""
    md5: str = ""
    symlink_target: str = ""

    @property
    def is_directory(self) -> bool:
        return (self.mode & 0o170000) == 0o040000

    def set_directory(self):
        # keep setuid/setgid/sticky: masking to 0o777 here would strip
        # them on every entry decode round-trip
        self.mode = (self.mode & 0o7777) | 0o040000


@dataclass
class Entry:
    full_path: str
    attr: Attr = field(default_factory=Attr)
    chunks: List[FileChunk] = field(default_factory=list)
    extended: Dict[str, bytes] = field(default_factory=dict)

    @property
    def is_directory(self) -> bool:
        return self.attr.is_directory

    @property
    def name(self) -> str:
        return posixpath.basename(self.full_path)

    @property
    def dir_name(self) -> str:
        return posixpath.dirname(self.full_path) or "/"

    def size(self) -> int:
        from .filechunks import total_size
        return total_size(self.chunks)

    def timestamp(self) -> float:
        return self.attr.crtime if self.is_directory else self.attr.mtime

    # -- codec (reference entry_codec.go; JSON instead of protobuf) --------

    def encode(self) -> bytes:
        a = self.attr
        d = {
            "path": self.full_path,
            "attr": {
                "mtime": a.mtime, "crtime": a.crtime, "mode": a.mode,
                "uid": a.uid, "gid": a.gid, "mime": a.mime,
                "replication": a.replication, "collection": a.collection,
                "ttlSec": a.ttl_sec, "userName": a.user_name, "md5": a.md5,
                "symlinkTarget": a.symlink_target,
            },
            "chunks": [c.to_dict() for c in self.chunks],
        }
        if self.extended:
            d["extended"] = {k: v.hex() for k, v in self.extended.items()}
        return json.dumps(d).encode()

    @classmethod
    def decode(cls, full_path: str, data: bytes) -> "Entry":
        d = json.loads(data)
        a = d.get("attr", {})
        attr = Attr(mtime=a.get("mtime", 0.0), crtime=a.get("crtime", 0.0),
                    mode=a.get("mode", 0o660), uid=a.get("uid", 0),
                    gid=a.get("gid", 0), mime=a.get("mime", ""),
                    replication=a.get("replication", ""),
                    collection=a.get("collection", ""),
                    ttl_sec=a.get("ttlSec", 0),
                    user_name=a.get("userName", ""),
                    md5=a.get("md5", ""),
                    symlink_target=a.get("symlinkTarget", ""))
        chunks = [FileChunk.from_dict(c) for c in d.get("chunks", [])]
        extended = {k: bytes.fromhex(v)
                    for k, v in d.get("extended", {}).items()}
        return cls(full_path=full_path, attr=attr, chunks=chunks,
                   extended=extended)


def entry_to_wire(e: Entry) -> dict:
    """Metadata-API wire shape (shared by FilerServer and FilerClient so
    the in-process and remote gateways cannot diverge)."""
    return {
        "FullPath": e.full_path,
        "Mtime": e.attr.mtime,
        "Crtime": e.attr.crtime,
        "Mode": e.attr.mode,
        "Uid": e.attr.uid,
        "Gid": e.attr.gid,
        "Mime": e.attr.mime,
        "Replication": e.attr.replication,
        "Collection": e.attr.collection,
        "TtlSec": e.attr.ttl_sec,
        "IsDirectory": e.is_directory,
        "Md5": e.attr.md5,
        "UserName": e.attr.user_name,
        "SymlinkTarget": e.attr.symlink_target,
        "chunks": [c.to_dict() for c in e.chunks],
        "extended": {k: v.hex() for k, v in (e.extended or {}).items()},
    }


def entry_from_wire(d: dict) -> Entry:
    import posixpath
    attr = Attr(mtime=d.get("Mtime", 0.0), crtime=d.get("Crtime", 0.0),
                mode=d.get("Mode", 0o660), uid=d.get("Uid", 0),
                gid=d.get("Gid", 0), mime=d.get("Mime", ""),
                replication=d.get("Replication", ""),
                collection=d.get("Collection", ""),
                ttl_sec=d.get("TtlSec", 0), md5=d.get("Md5", ""),
                user_name=d.get("UserName", ""),
                symlink_target=d.get("SymlinkTarget", ""))
    if d.get("IsDirectory"):
        attr.set_directory()
    chunks = [FileChunk.from_dict(c) for c in d.get("chunks", [])]
    extended = {k: bytes.fromhex(v)
                for k, v in d.get("extended", {}).items()}
    # normalize on ingest: lookups normpath their paths, so an entry
    # created with an un-normalized path would be unreachable
    return Entry(full_path=posixpath.normpath(d["FullPath"]),
                 attr=attr, chunks=chunks, extended=extended)


def new_dir_entry(path: str, now: Optional[float] = None) -> Entry:
    now = time.time() if now is None else now
    attr = Attr(mtime=now, crtime=now, mode=0o777)
    attr.set_directory()
    return Entry(full_path=path, attr=attr)
