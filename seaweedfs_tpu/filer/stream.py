"""Streaming reads of chunked files from volume servers.

Reference weed/filer2/stream.go:15-145 (StreamContent) and reader_at.go
(random access): plan ChunkViews for the range, fetch each chunk slice
from a volume location, reassemble in order.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from .entry import FileChunk
from .filechunks import view_from_chunks
from ..util import config as _config


def default_fetcher(master_url: str):
    from ..client.operation import VidCache
    from ..server.http_util import HttpError, http_call
    from ..storage.types import parse_file_id
    cache = VidCache(master_url, watch=True)

    import time as _time

    def fetch(fid: str, offset: int, size: int) -> bytes:
        vid, _, _ = parse_file_id(fid)
        headers = {}
        if size >= 0:
            headers["Range"] = f"bytes={offset}-{offset + size - 1}"
        last: Optional[Exception] = None
        # two rounds: if every cached holder fails at transport/server
        # level (node died between the lookup and the read), discard
        # the dead routes — including from the push-updated vid map —
        # and try the refreshed set once more. Deterministic 4xx (a
        # vacuumed chunk) never retries: it would just double latency.
        for round_ in range(2):
            failed = []
            for url in cache.lookup_read(vid):
                try:
                    return http_call("GET", f"http://{url}/{fid}",
                                     headers=headers)
                except HttpError as e:
                    last = e
                    failed.append(url)
            cache.invalidate(vid, failed_urls=failed)
            if last is not None and last.status < 500:
                break
            if round_ == 0:
                _time.sleep(_config.retry_backoff_s(0.5))
        raise last or HttpError(404, f"no locations for {fid}")

    return fetch


def read_chunked(chunks: List[FileChunk], offset: int, size: int,
                 fetch: Callable[[str, int, int], bytes]) -> bytes:
    """Assemble [offset, offset+size) of the logical file; gaps between
    chunks read as zeros (sparse-file semantics, reference stream.go)."""
    views = view_from_chunks(chunks, offset, size)
    if size < 0:
        from .filechunks import total_size
        size = max(total_size(chunks) - offset, 0)
    out = bytearray(size)
    for v in views:
        if v.cipher_key or v.is_compressed:
            # encrypted/gzipped blobs can't be range-read on the volume
            # server: fetch whole, transform, then slice the view window
            # (reference stream.go fetchChunk + DecryptData/UnGzipData)
            blob = fetch(v.fid, 0, -1)
            if v.cipher_key:
                from ..util import decrypt
                blob = decrypt(blob, v.cipher_key)
            if v.is_compressed:
                from ..util import gunzip_data
                blob = gunzip_data(blob)
            data = blob[v.offset:v.offset + v.size]
        else:
            data = fetch(v.fid, v.offset, v.size)
        start = v.logical_offset - offset
        out[start:start + len(data)] = data
    return bytes(out)


def stream_chunked(chunks: List[FileChunk], fetch, out) -> int:
    """Write the whole logical file into file-like `out`, one chunk view
    at a time — bounded memory regardless of file size (the RAM-bound
    alternative to read_chunked for replication and export paths). Gaps
    between chunks write as zeros. Returns total bytes written."""
    from .filechunks import total_size
    size = total_size(chunks)
    views = view_from_chunks(chunks, 0, size)
    pos = 0
    for v in views:
        if v.logical_offset > pos:
            _write_zeros(out, v.logical_offset - pos)
            pos = v.logical_offset
        if v.cipher_key or v.is_compressed:
            blob = fetch(v.fid, 0, -1)
            if v.cipher_key:
                from ..util import decrypt
                blob = decrypt(blob, v.cipher_key)
            if v.is_compressed:
                from ..util import gunzip_data
                blob = gunzip_data(blob)
            data = blob[v.offset:v.offset + v.size]
        else:
            data = fetch(v.fid, v.offset, v.size)
        out.write(data)
        pos += len(data)
    if pos < size:
        _write_zeros(out, size - pos)
        pos = size
    return pos


def _write_zeros(out, n: int, block: int = 1 << 20):
    zeros = b"\x00" * min(n, block)
    while n > 0:
        out.write(zeros[:min(n, block)])
        n -= block
