"""Streaming reads of chunked files from volume servers.

Reference weed/filer2/stream.go:15-145 (StreamContent) and reader_at.go
(random access): plan ChunkViews for the range, fetch each chunk slice
from a volume location, reassemble in order.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from .entry import FileChunk
from .filechunks import view_from_chunks


def default_fetcher(master_url: str):
    from ..client.operation import VidCache
    from ..server.http_util import HttpError, http_call
    from ..storage.types import parse_file_id
    cache = VidCache(master_url)

    def fetch(fid: str, offset: int, size: int) -> bytes:
        vid, _, _ = parse_file_id(fid)
        last: Optional[Exception] = None
        for url in cache.lookup(vid):
            try:
                return http_call(
                    "GET", f"http://{url}/{fid}",
                    headers={"Range": f"bytes={offset}-{offset+size-1}"})
            except HttpError as e:
                last = e
                cache.invalidate(vid)
        raise last or HttpError(404, f"no locations for {fid}")

    return fetch


def read_chunked(chunks: List[FileChunk], offset: int, size: int,
                 fetch: Callable[[str, int, int], bytes]) -> bytes:
    """Assemble [offset, offset+size) of the logical file; gaps between
    chunks read as zeros (sparse-file semantics, reference stream.go)."""
    views = view_from_chunks(chunks, offset, size)
    if size < 0:
        from .filechunks import total_size
        size = max(total_size(chunks) - offset, 0)
    out = bytearray(size)
    for v in views:
        data = fetch(v.fid, v.offset, v.size)
        start = v.logical_offset - offset
        out[start:start + len(data)] = data
    return bytes(out)
