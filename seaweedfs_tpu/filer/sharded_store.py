"""Sharded embedded filer store (reference weed/filer2/leveldb2).

The reference's leveldb2 store splits the namespace across 8 embedded
leveldb instances by md5(directory) so one hot directory (or one huge
db) never serializes the whole filer; keys are md5(dir)+name so a
directory's children colocate in exactly one shard and listings stay a
single range scan. The same design over the stdlib's sqlite: N
independent database files, shard = md5(dir) % N.

Cross-shard operations: only recursive folder deletion spans shards
(descendant directories hash elsewhere); it broadcasts the prefix
delete to every shard, exactly as cheap as the single-db case in
aggregate.
"""

from __future__ import annotations

import hashlib
import os
import posixpath
from typing import List, Optional

from .entry import Entry
from .filerstore import FilerStore, register_store
from .sqlite_store import SqliteStore

DEFAULT_SHARDS = 8


@register_store
class ShardedStore(FilerStore):
    name = "sharded"

    def initialize(self, path: str = "", shards: int = DEFAULT_SHARDS,
                   **options):
        """``path`` is a directory holding filer_00.db .. filer_NN.db
        (empty/':memory:' -> per-shard in-memory dbs, for tests).

        The shard count is sticky: it is recorded in a SHARDS marker on
        first open and re-used afterwards — reopening with a different
        ``shards`` value would re-route md5(dir) % N and silently hide
        every existing entry."""
        self._n = int(shards)
        self._shards: List[SqliteStore] = []
        if path and path != ":memory:":
            os.makedirs(path, exist_ok=True)
            marker = os.path.join(path, "SHARDS")
            if os.path.exists(marker):
                with open(marker) as f:
                    self._n = int(f.read().strip())
            else:
                existing = [p for p in os.listdir(path)
                            if p.startswith("filer_") and p.endswith(".db")]
                if existing and len(existing) != self._n:
                    self._n = len(existing)
                with open(marker, "w") as f:
                    f.write(str(self._n))
        for i in range(self._n):
            s = SqliteStore()
            if path and path != ":memory:":
                s.initialize(path=os.path.join(path, f"filer_{i:02d}.db"))
            else:
                s.initialize(path=":memory:")
            self._shards.append(s)

    def _shard_for_dir(self, dir_path: str) -> SqliteStore:
        digest = hashlib.md5(
            (dir_path.rstrip("/") or "/").encode()).digest()
        return self._shards[digest[0] % self._n]

    def _shard(self, full_path: str) -> SqliteStore:
        return self._shard_for_dir(posixpath.dirname(full_path) or "/")

    def insert_entry(self, entry: Entry) -> None:
        self._shard(entry.full_path).insert_entry(entry)

    def update_entry(self, entry: Entry) -> None:
        self._shard(entry.full_path).update_entry(entry)

    def find_entry(self, full_path: str) -> Optional[Entry]:
        return self._shard(full_path).find_entry(full_path)

    def delete_entry(self, full_path: str) -> None:
        self._shard(full_path).delete_entry(full_path)

    def delete_folder_children(self, full_path: str) -> None:
        # descendants' directories hash to arbitrary shards: broadcast
        # (reference leveldb2 walks its per-shard prefix the same way)
        for s in self._shards:
            s.delete_folder_children(full_path)

    def list_directory_entries(self, dir_path: str, start_file_name: str,
                               inclusive: bool, limit: int) -> List[Entry]:
        return self._shard_for_dir(dir_path).list_directory_entries(
            dir_path, start_file_name, inclusive, limit)

    def close(self):
        for s in self._shards:
            s.close()
