"""Minimal ctypes binding to libfuse2's high-level API.

The environment ships libfuse.so.2 (2.9) but no Python FUSE package, so
this binds the handful of fuse_operations the mount needs directly.
Struct layouts follow FUSE_USE_VERSION 26 on x86-64 Linux (fuse.h of
libfuse 2.9.x) — getattr/readdir/open/create/read/write/truncate/
unlink/mkdir/rmdir/rename/flush/release/utimens/chmod.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
from typing import Callable, List, Optional


class FuseError(Exception):
    pass


def _load_libfuse():
    for name in ("libfuse.so.2", ctypes.util.find_library("fuse")):
        if not name:
            continue
        try:
            return ctypes.CDLL(name)
        except OSError:
            continue
    raise FuseError(
        "libfuse.so.2 not found — `weed-tpu mount` needs FUSE; use the "
        "WebDAV gateway or filer HTTP API instead")


c_off_t = ctypes.c_int64
c_mode_t = ctypes.c_uint32
c_dev_t = ctypes.c_uint64
c_uid_t = ctypes.c_uint32
c_gid_t = ctypes.c_uint32


class Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


class Stat(ctypes.Structure):
    # x86-64 Linux struct stat
    _fields_ = [
        ("st_dev", ctypes.c_uint64),
        ("st_ino", ctypes.c_uint64),
        ("st_nlink", ctypes.c_uint64),
        ("st_mode", ctypes.c_uint32),
        ("st_uid", ctypes.c_uint32),
        ("st_gid", ctypes.c_uint32),
        ("__pad0", ctypes.c_int),
        ("st_rdev", ctypes.c_uint64),
        ("st_size", ctypes.c_int64),
        ("st_blksize", ctypes.c_int64),
        ("st_blocks", ctypes.c_int64),
        ("st_atim", Timespec),
        ("st_mtim", Timespec),
        ("st_ctim", Timespec),
        ("__reserved", ctypes.c_int64 * 3),
    ]


class FuseFileInfo(ctypes.Structure):
    # fuse_common.h (v26)
    _fields_ = [
        ("flags", ctypes.c_int),
        ("fh_old", ctypes.c_ulong),
        ("writepage", ctypes.c_int),
        ("bits", ctypes.c_uint),        # direct_io/keep_cache/... bitfield
        ("fh", ctypes.c_uint64),
        ("lock_owner", ctypes.c_uint64),
    ]


FILL_DIR_T = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p,
    ctypes.POINTER(Stat), c_off_t)

_GETATTR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                              ctypes.POINTER(Stat))
_READLINK_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_char),
                               ctypes.c_size_t)
_SETXATTR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                               ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_char),
                               ctypes.c_size_t, ctypes.c_int)
_GETXATTR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                               ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_char),
                               ctypes.c_size_t)
_LISTXATTR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_char),
                                ctypes.c_size_t)
_REMOVEXATTR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                                  ctypes.c_char_p)
_GETDIR_T = ctypes.c_void_p
_MKNOD_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, c_mode_t,
                            c_dev_t)
_MKDIR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, c_mode_t)
_PATH_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p)
_PATH2_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                            ctypes.c_char_p)
_CHMOD_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, c_mode_t)
_CHOWN_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, c_uid_t,
                            c_gid_t)
_TRUNCATE_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, c_off_t)
_OPEN_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                           ctypes.POINTER(FuseFileInfo))
_READ_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                           ctypes.POINTER(ctypes.c_char),
                           ctypes.c_size_t, c_off_t,
                           ctypes.POINTER(FuseFileInfo))
_WRITE_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                            ctypes.POINTER(ctypes.c_char),
                            ctypes.c_size_t, c_off_t,
                            ctypes.POINTER(FuseFileInfo))
_FSYNC_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                            ctypes.c_int,
                            ctypes.POINTER(FuseFileInfo))
_READDIR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                              ctypes.c_void_p, FILL_DIR_T, c_off_t,
                              ctypes.POINTER(FuseFileInfo))
_CREATE_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, c_mode_t,
                             ctypes.POINTER(FuseFileInfo))
_UTIMENS_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                              ctypes.POINTER(Timespec))


class FuseOperations(ctypes.Structure):
    # fuse.h v26 field order — do not reorder
    _fields_ = [
        ("getattr", _GETATTR_T),
        ("readlink", _READLINK_T),
        ("getdir", _GETDIR_T),
        ("mknod", _MKNOD_T),
        ("mkdir", _MKDIR_T),
        ("unlink", _PATH_T),
        ("rmdir", _PATH_T),
        ("symlink", _PATH2_T),
        ("rename", _PATH2_T),
        ("link", _PATH2_T),
        ("chmod", _CHMOD_T),
        ("chown", _CHOWN_T),
        ("truncate", _TRUNCATE_T),
        ("utime", ctypes.c_void_p),
        ("open", _OPEN_T),
        ("read", _READ_T),
        ("write", _WRITE_T),
        ("statfs", ctypes.c_void_p),
        ("flush", _OPEN_T),
        ("release", _OPEN_T),
        ("fsync", _FSYNC_T),
        ("setxattr", _SETXATTR_T),
        ("getxattr", _GETXATTR_T),
        ("listxattr", _LISTXATTR_T),
        ("removexattr", _REMOVEXATTR_T),
        ("opendir", _OPEN_T),
        ("readdir", _READDIR_T),
        ("releasedir", _OPEN_T),
        ("fsyncdir", _FSYNC_T),
        ("init", ctypes.c_void_p),
        ("destroy", ctypes.c_void_p),
        ("access", ctypes.c_void_p),
        ("create", _CREATE_T),
        ("ftruncate", ctypes.c_void_p),
        ("fgetattr", ctypes.c_void_p),
        ("lock", ctypes.c_void_p),
        ("utimens", _UTIMENS_T),
        ("bmap", ctypes.c_void_p),
        ("flags", ctypes.c_uint),
        ("ioctl", ctypes.c_void_p),
        ("poll", ctypes.c_void_p),
        ("write_buf", ctypes.c_void_p),
        ("read_buf", ctypes.c_void_p),
        ("flock", ctypes.c_void_p),
        ("fallocate", ctypes.c_void_p),
    ]


def _wrap(fn: Callable, functype, name: str):
    """C callback that maps Python exceptions to -errno."""

    def call(*args):
        try:
            out = fn(*args)
            return 0 if out is None else out
        except OSError as e:
            return -(e.errno or errno.EIO)
        except Exception:  # noqa: BLE001 — must never unwind into C
            return -errno.EIO
    return functype(call)


class FuseMount:
    """Mount `ops` (an object with optional getattr/readdir/... methods
    returning 0/-errno or raising OSError) at mountpoint and serve
    until unmounted. Blocks the calling thread."""

    def __init__(self, ops, mountpoint: str, foreground: bool = True,
                 allow_other: bool = False, fsname: str = "seaweedfs"):
        self.lib = _load_libfuse()
        self.ops = ops
        self.mountpoint = mountpoint
        args = ["weed-tpu-mount", mountpoint, "-s"]   # single-threaded
        if foreground:
            args.append("-f")
        opts = [f"fsname={fsname}", "default_permissions"]
        if allow_other:
            opts.append("allow_other")
        args += ["-o", ",".join(opts)]
        self.argv = (ctypes.c_char_p * len(args))(
            *[a.encode() for a in args])
        self.argc = len(args)

        self.c_ops = FuseOperations()
        self._keep = []       # keep callback objects alive
        table = [
            ("getattr", _GETATTR_T), ("mkdir", _MKDIR_T),
            ("unlink", _PATH_T), ("rmdir", _PATH_T),
            ("rename", _PATH2_T), ("chmod", _CHMOD_T),
            ("chown", _CHOWN_T),
            ("truncate", _TRUNCATE_T), ("open", _OPEN_T),
            ("read", _READ_T), ("write", _WRITE_T),
            ("flush", _OPEN_T), ("release", _OPEN_T),
            ("readdir", _READDIR_T), ("create", _CREATE_T),
            ("utimens", _UTIMENS_T),
            ("readlink", _READLINK_T), ("symlink", _PATH2_T),
            ("setxattr", _SETXATTR_T), ("getxattr", _GETXATTR_T),
            ("listxattr", _LISTXATTR_T),
            ("removexattr", _REMOVEXATTR_T),
        ]
        for name, ftype in table:
            fn = getattr(ops, name, None)
            if fn is not None:
                cb = _wrap(fn, ftype, name)
                self._keep.append(cb)
                setattr(self.c_ops, name, cb)

    def run(self) -> int:
        main = self.lib.fuse_main_real
        main.restype = ctypes.c_int
        main.argtypes = [ctypes.c_int,
                         ctypes.POINTER(ctypes.c_char_p),
                         ctypes.POINTER(FuseOperations),
                         ctypes.c_size_t, ctypes.c_void_p]
        return main(self.argc, self.argv, ctypes.byref(self.c_ops),
                    ctypes.sizeof(self.c_ops), None)
