"""FUSE mount (reference weed/filesys/): the filer namespace as a
local filesystem, via a ctypes binding to libfuse2."""

from .dirty_pages import ContinuousIntervals  # noqa: F401
