"""Dirty write buffering — merged in-memory intervals per open file.

Reference weed/filesys/dirty_page_interval.go: writes land in
non-overlapping intervals (newer data wins on overlap); a flush walks
them in order and uploads each run as a chunk. This is the pure logic
core of the mount's write path, testable without FUSE.
"""

from __future__ import annotations

from typing import List, Optional, Tuple


class _Interval:
    __slots__ = ("offset", "data")

    def __init__(self, offset: int, data):
        self.offset = offset
        # bytearray: sequential writes extend the last run in amortized
        # O(appended) — bytes concatenation would re-copy the whole
        # accumulated run on every 128KB FUSE write (O(n^2) total)
        self.data = bytearray(data)

    @property
    def end(self) -> int:
        return self.offset + len(self.data)


class ContinuousIntervals:
    """Sorted, non-overlapping dirty intervals; adjacent runs merge."""

    def __init__(self):
        self.intervals: List[_Interval] = []

    def size(self) -> int:
        return self.intervals[-1].end if self.intervals else 0

    def total_bytes(self) -> int:
        return sum(len(iv.data) for iv in self.intervals)

    def add(self, offset: int, data: bytes):
        """Newer data overwrites any overlapped older bytes
        (reference AddInterval)."""
        if not data:
            return
        # hot path: a sequential write extends the trailing run in place
        # (intervals are sorted and disjoint, so offset == last.end
        # cannot overlap anything)
        if self.intervals and offset == self.intervals[-1].end:
            self.intervals[-1].data += data
            return
        new = _Interval(offset, data)
        out: List[_Interval] = []
        for iv in self.intervals:
            if iv.end <= new.offset or iv.offset >= new.end:
                out.append(iv)                      # disjoint: reuse
                continue
            if iv.offset < new.offset:              # keep left remnant
                out.append(_Interval(
                    iv.offset, iv.data[:new.offset - iv.offset]))
            if iv.end > new.end:                    # keep right remnant
                out.append(_Interval(
                    new.end, iv.data[new.end - iv.offset:]))
        out.append(new)
        out.sort(key=lambda iv: iv.offset)
        # merge touching runs so a flush uploads maximal chunks
        merged: List[_Interval] = []
        for iv in out:
            if merged and merged[-1].end == iv.offset:
                merged[-1].data += iv.data
            else:
                merged.append(iv)
        self.intervals = merged

    def read_at(self, buf: bytearray, offset: int) -> int:
        """Overlay dirty bytes onto buf (which holds the stored
        content); returns the max end position filled (reference
        ReadDataAt)."""
        max_stop = 0
        for iv in self.intervals:
            start = max(iv.offset, offset)
            stop = min(iv.end, offset + len(buf))
            if start >= stop:
                continue
            buf[start - offset:stop - offset] = \
                iv.data[start - iv.offset:stop - iv.offset]
            max_stop = max(max_stop, stop)
        return max_stop

    def truncate(self, length: int):
        """Drop dirty bytes at/after length (an ftruncate while the
        handle holds buffered writes)."""
        out: List[_Interval] = []
        for iv in self.intervals:
            if iv.offset >= length:
                continue
            if iv.end > length:
                out.append(_Interval(iv.offset,
                                     iv.data[:length - iv.offset]))
            else:
                out.append(iv)
        self.intervals = out

    def pop_largest(self) -> Optional[Tuple[int, bytes]]:
        """Remove and return the largest run (the reference's
        saveExistingLargestPageToStorage spill policy,
        weed/filesys/dirty_page.go)."""
        if not self.intervals:
            return None
        idx = max(range(len(self.intervals)),
                  key=lambda i: len(self.intervals[i].data))
        iv = self.intervals.pop(idx)
        return iv.offset, bytes(iv.data)

    def pop_all(self) -> List[Tuple[int, bytes]]:
        out = [(iv.offset, bytes(iv.data)) for iv in self.intervals]
        self.intervals = []
        return out
