"""WFS — the filer namespace served through FUSE.

Reference weed/filesys/wfs.go + dir.go + file.go + filehandle.go: each
open file buffers writes as merged dirty intervals; a flush uploads
each run as a chunk whose logical offset overlaps older chunks, and the
chunk model's visible-interval resolution (newest mtime wins) yields
the right bytes on read — the same overlap semantics the reference
relies on.
"""

from __future__ import annotations

import ctypes
import errno
import posixpath
import stat as stat_mod
import time
from typing import Dict, Optional

from ..filer.entry import Attr, Entry, FileChunk
from ..filer.filechunks import total_size
from ..filer.filer import FilerError, NotFoundError
from ..filer.filer_client import FilerClient
from ..filer.stream import default_fetcher, read_chunked
from ..filer.upload import split_and_upload
from ..server.http_util import HttpError, get_json
from .dirty_pages import ContinuousIntervals
from .fuse_ll import Stat, Timespec


class _Handle:
    def __init__(self, entry: Entry):
        self.entry = entry
        self.dirty = ContinuousIntervals()
        self.new_size = None      # set by truncate while open
        # chunks already uploaded by the write-path spill but not yet
        # attached to the entry (that happens at flush)
        self.pending_chunks = []


class WeedFS:
    """fuse_operations receiver; methods return 0/-errno."""

    def __init__(self, filer_url: str, master_url: str = "",
                 chunk_size: int = 8 << 20, collection: str = "",
                 replication: str = "", root_path: str = "/"):
        self.client = FilerClient(filer_url)
        self.filer_url = filer_url
        # -filer.path: mount a remote subtree (reference mount.go:29
        # filerMountRootPath) — every kernel path maps under it
        self.root_path = "/" + root_path.strip("/") \
            if root_path.strip("/") else "/"
        # root stats are on every path resolution: briefly cache the
        # subtree root's ABSENCE so a slow/down filer can't stall each
        # one for a full HTTP timeout (present roots always re-stat —
        # external attr changes stay immediately visible); any local op
        # that could materialize the subtree clears the cache
        self._root_absent_until = 0.0
        self._root_cache_ttl = 1.0
        if not master_url:
            master_url = get_json(
                f"http://{filer_url}/filer/status")["master"]
        self.master_url = master_url
        self.chunk_size = chunk_size
        self.collection = collection
        self.replication = replication
        self._fetch = default_fetcher(master_url)
        self.handles: Dict[int, _Handle] = {}
        self._next_fh = 1

    # -- helpers -----------------------------------------------------------
    def _path(self, raw) -> str:
        """Decode only — for xattr names and symlink targets, which are
        not filer paths and must never be root-remapped."""
        return raw.decode() if isinstance(raw, bytes) else raw

    def _fpath(self, raw) -> str:
        """Kernel path -> filer path (under -filer.path when set)."""
        p = self._path(raw)
        if self.root_path != "/":
            p = self.root_path if p == "/" else self.root_path + p
        return p

    def _entry(self, path: str) -> Entry:
        try:
            return self.client.find_entry(path)
        except (NotFoundError, HttpError):
            raise OSError(errno.ENOENT, path)

    def _fill_stat(self, st, entry: Optional[Entry]):
        ctypes.memset(ctypes.addressof(st.contents), 0,
                      ctypes.sizeof(Stat))
        s = st.contents
        if entry is None:             # the mount root
            s.st_mode = stat_mod.S_IFDIR | 0o755
            s.st_nlink = 2
            return
        mode = entry.attr.mode & 0o7777
        # file-type bits on the stored mode mean the permission bits
        # were explicitly set (chmod keeps them) — honor even 0000;
        # entries from non-FUSE writers get per-kind defaults when
        # their bare mode is 0
        explicit = bool(entry.attr.mode & 0o170000)
        if entry.is_directory:
            s.st_mode = stat_mod.S_IFDIR | \
                (mode if explicit else (mode or 0o755))
            s.st_nlink = 2
        elif entry.attr.symlink_target:
            # a symlink's size is its target length (reference
            # weed/filesys/dir_link.go:36 os.ModeSymlink)
            s.st_mode = stat_mod.S_IFLNK | \
                (mode if explicit else (mode or 0o777))
            s.st_nlink = 1
            s.st_size = len(entry.attr.symlink_target.encode())
        else:
            s.st_mode = stat_mod.S_IFREG | \
                (mode if explicit else (mode or 0o644))
            s.st_nlink = 1
            s.st_size = total_size(entry.chunks)
        s.st_uid = entry.attr.uid
        s.st_gid = entry.attr.gid
        ts = int(entry.attr.mtime or time.time())
        s.st_mtim.tv_sec = ts
        s.st_ctim.tv_sec = int(entry.attr.crtime or ts)
        s.st_atim.tv_sec = ts
        s.st_blksize = 512
        s.st_blocks = (s.st_size + 511) // 512

    def _read_stored(self, entry: Entry, offset: int, size: int,
                     extra_chunks=None) -> bytes:
        chunks = list(entry.chunks) + list(extra_chunks or [])
        if not chunks:
            return b""
        want = min(size, max(0, total_size(chunks) - offset))
        if want <= 0:
            return b""
        return read_chunked(chunks, offset, want, self._fetch)

    # -- fuse_operations ---------------------------------------------------
    def getattr(self, path, st):
        if self._path(path) == "/":
            # the mount root: report the remote entry's real
            # attributes when it exists (so chmod/chown on the root of
            # a -filer.path subtree read back correctly), but a stat
            # must still succeed before the first write creates the
            # subtree — hence the synthetic directory fallback
            entry = None
            if self.root_path != "/" and \
                    time.monotonic() >= self._root_absent_until:
                try:
                    entry = self._entry(self.root_path)
                except OSError:
                    entry = None
                if entry is not None and not entry.is_directory:
                    entry = None
                if entry is None:
                    self._root_absent_until = \
                        time.monotonic() + self._root_cache_ttl
            self._fill_stat(st, entry)
            return 0
        self._fill_stat(st, self._entry(self._fpath(path)))
        return 0

    def readdir(self, path, buf, filler, offset, fi):
        p = self._fpath(path)
        filler(buf, b".", None, 0)
        filler(buf, b"..", None, 0)
        start = ""
        while True:
            batch = self.client.list_entries(p, start_file=start,
                                             limit=1000)
            for e in batch:
                filler(buf, e.name.encode(), None, 0)
            if len(batch) < 1000:
                return 0
            start = batch[-1].name

    def mkdir(self, path, mode):
        self._root_absent_until = 0.0  # may materialize the subtree
        p = self._fpath(path)
        now = time.time()
        entry = Entry(full_path=p,
                      attr=Attr(mtime=now, crtime=now,
                                mode=mode & 0o7777))
        entry.attr.set_directory()
        try:
            self.client.create_entry(entry)
        except FilerError:
            raise OSError(errno.EEXIST, p)
        return 0

    def unlink(self, path):
        self._delete(self._fpath(path), recursive=False)
        return 0

    def rmdir(self, path):
        p = self._fpath(path)
        if self.client.list_entries(p, limit=1):
            raise OSError(errno.ENOTEMPTY, p)
        self._delete(p, recursive=False)
        return 0

    def _delete(self, p: str, recursive: bool):
        try:
            self.client.delete_entry(p, recursive=recursive,
                                     ignore_recursive_error=False)
        except NotFoundError:
            raise OSError(errno.ENOENT, p)
        except FilerError:
            raise OSError(errno.ENOTEMPTY, p)
        except HttpError as e:
            raise OSError(errno.ENOENT if e.status == 404 else
                          errno.EIO, p)

    def rename(self, old, new):
        try:
            self.client.rename_entry(self._fpath(old), self._fpath(new))
        except NotFoundError:
            raise OSError(errno.ENOENT, self._fpath(old))
        return 0

    def chmod(self, path, mode):
        entry = self._entry(self._fpath(path))
        # keep the file-type bits: they preserve the entry kind AND mark
        # the permission bits as explicitly set, so a chmod 0000 reads
        # back as 0000 instead of _fill_stat's legacy-entry default
        if entry.is_directory:
            kind = 0o040000
        elif getattr(entry.attr, "symlink_target", ""):
            kind = 0o120000
        else:
            kind = 0o100000
        entry.attr.mode = (mode & 0o7777) | kind
        self.client.update_entry(entry)
        return 0

    def chown(self, path, uid, gid):
        entry = self._entry(self._fpath(path))
        entry.attr.uid, entry.attr.gid = uid, gid
        self.client.update_entry(entry)
        return 0

    def utimens(self, path, times):
        entry = self._entry(self._fpath(path))
        if times:
            entry.attr.mtime = times[1].tv_sec
        else:
            entry.attr.mtime = time.time()
        self.client.update_entry(entry)
        return 0

    # -- symlinks (reference weed/filesys/dir_link.go:15-45) ---------------
    def symlink(self, target, linkpath):
        self._root_absent_until = 0.0  # may materialize the subtree
        p = self._fpath(linkpath)
        now = time.time()
        entry = Entry(full_path=p,
                      attr=Attr(mtime=now, crtime=now, mode=0o777))
        entry.attr.symlink_target = self._path(target)
        try:
            self.client.create_entry(entry)
        except FilerError:
            # EEXIST only for a genuine duplicate; a transient filer
            # failure misreported as "File exists" would send the user
            # chasing a file that isn't there
            try:
                self.client.find_entry(p)
            except (NotFoundError, HttpError):
                raise OSError(errno.EIO, p)
            raise OSError(errno.EEXIST, p)
        return 0

    def readlink(self, path, buf, size):
        entry = self._entry(self._fpath(path))
        target = entry.attr.symlink_target
        if not target:
            raise OSError(errno.EINVAL, "not a symlink")
        # null-terminated, truncated to the buffer (libfuse2 contract)
        data = target.encode()[:max(0, size - 1)]
        ctypes.memmove(buf, data, len(data))
        buf[len(data)] = b"\x00"
        return 0

    # -- extended attributes (reference weed/filesys/xattr.go) -------------
    _XATTR_CREATE, _XATTR_REPLACE = 1, 2

    def setxattr(self, path, name, value, size, flags):
        entry = self._entry(self._fpath(path))
        key = self._path(name)
        exists = key in (entry.extended or {})
        if flags & self._XATTR_CREATE and exists:
            raise OSError(errno.EEXIST, key)
        if flags & self._XATTR_REPLACE and not exists:
            raise OSError(errno.ENODATA, key)
        if entry.extended is None:
            entry.extended = {}
        entry.extended[key] = ctypes.string_at(value, size) \
            if size else b""
        self.client.update_entry(entry)
        return 0

    def getxattr(self, path, name, buf, size):
        entry = self._entry(self._fpath(path))
        data = (entry.extended or {}).get(self._path(name))
        if data is None:
            raise OSError(errno.ENODATA, self._path(name))
        if size == 0:            # size probe
            return len(data)
        if size < len(data):
            raise OSError(errno.ERANGE, self._path(name))
        ctypes.memmove(buf, data, len(data))
        return len(data)

    def listxattr(self, path, buf, size):
        entry = self._entry(self._fpath(path))
        blob = b"".join(k.encode() + b"\x00"
                        for k in sorted(entry.extended or {}))
        if size == 0:
            return len(blob)
        if size < len(blob):
            raise OSError(errno.ERANGE, self._fpath(path))
        ctypes.memmove(buf, blob, len(blob))
        return len(blob)

    def removexattr(self, path, name):
        entry = self._entry(self._fpath(path))
        key = self._path(name)
        if key not in (entry.extended or {}):
            raise OSError(errno.ENODATA, key)
        del entry.extended[key]
        self.client.update_entry(entry)
        return 0

    def create(self, path, mode, fi):
        self._root_absent_until = 0.0  # may materialize the subtree
        p = self._fpath(path)
        now = time.time()
        # stamp the type bits: FUSE-created files carry an explicitly
        # chosen mode, so open(path, O_CREAT, 0000) must read back as
        # 0000 (same semantics as mkdir/chmod), not the legacy default
        entry = Entry(full_path=p,
                      attr=Attr(mtime=now, crtime=now,
                                mode=(mode & 0o7777) | 0o100000))
        try:
            self.client.create_entry(entry)
        except FilerError:
            entry = self._entry(p)     # already exists: open it
        fi.contents.fh = self._open_handle(entry)
        return 0

    def open(self, path, fi):
        entry = self._entry(self._fpath(path))
        fi.contents.fh = self._open_handle(entry)
        return 0

    def _open_handle(self, entry: Entry) -> int:
        fh = self._next_fh
        self._next_fh += 1
        self.handles[fh] = _Handle(entry)
        return fh

    def _handle(self, fi) -> _Handle:
        h = self.handles.get(fi.contents.fh)
        if h is None:
            raise OSError(errno.EBADF, "stale handle")
        return h

    def read(self, path, buf, size, offset, fi):
        h = self._handle(fi)
        eff_size = total_size(h.entry.chunks)
        if h.new_size is not None:
            eff_size = h.new_size
        eff_size = max(eff_size, h.dirty.size(),
                       total_size(h.pending_chunks))
        if offset >= eff_size:
            return 0
        want = min(size, eff_size - offset)
        out = bytearray(want)
        stored = self._read_stored(h.entry, offset, want, h.pending_chunks)
        out[:len(stored)] = stored
        h.dirty.read_at(out, offset)
        ctypes.memmove(buf, bytes(out), len(out))
        return len(out)

    def write(self, path, buf, size, offset, fi):
        h = self._handle(fi)
        data = ctypes.string_at(buf, size)
        h.dirty.add(offset, data)
        self._maybe_spill(h)
        return size

    def _maybe_spill(self, h: "_Handle"):
        """Bound the dirty-page RAM: once buffered bytes exceed one chunk,
        upload the largest run now and attach it at flush (the reference's
        saveExistingLargestPageToStorage, weed/filesys/dirty_page.go) —
        without this, copying a large file through the mount holds the
        whole file in memory."""
        while h.dirty.total_bytes() > self.chunk_size:
            popped = h.dirty.pop_largest()
            if popped is None:
                break
            run_offset, data = popped
            landed = []
            try:
                chunks, _ = split_and_upload(
                    self.master_url, data, h.entry.name, self.chunk_size,
                    collection=self.collection,
                    replication=self.replication, uploaded=landed)
            except Exception:
                # keep the data buffered so nothing is lost; surface the
                # error to the writer (fuse_ll maps it to -EIO). Chunks
                # that already landed before the failing piece would be
                # re-uploaded on retry — queue them for deletion so they
                # don't leak on volume servers.
                h.dirty.add(run_offset, data)
                self._queue_deletion_quiet(landed)
                raise
            for c in chunks:
                c.offset += run_offset
            h.pending_chunks.extend(chunks)

    def truncate(self, path, length):
        """Path truncate — fuse2 also routes ftruncate here (the
        ftruncate slot is NULL). Open handles holding buffered writes
        (dirty runs or spilled pending chunks) are flushed first so the
        truncate operates on the complete logical content; otherwise the
        materialize-to-length step would read only the stored chunks and
        overwrite the unflushed bytes with zeros (and a later flush could
        resurrect cut bytes)."""
        p = self._fpath(path)
        for h in self.handles.values():
            if h.entry.full_path == p and (h.dirty.intervals
                                           or h.pending_chunks):
                self._do_flush(h)
        entry = self._entry(p)
        self._truncate_entry(entry, length)
        for h in self.handles.values():
            if h.entry.full_path == p:
                h.dirty.truncate(length)
                h.new_size = length
                h.entry = entry
        return 0

    def _truncate_entry(self, entry: Entry, length: int):
        current = total_size(entry.chunks)
        if length == current:
            return
        old_chunks = list(entry.chunks)
        if length == 0:
            entry.chunks = []
        else:
            # materialize to the new size and re-chunk — the chunk
            # model has no truncate marker
            content = self._read_stored(entry, 0, length)
            content = content.ljust(length, b"\x00")
            landed: list = []
            try:
                chunks, _ = split_and_upload(
                    self.master_url, content, entry.name,
                    self.chunk_size, collection=self.collection,
                    replication=self.replication, uploaded=landed)
            except Exception:
                self._queue_deletion_quiet(landed)
                raise
            entry.chunks = chunks
        entry.attr.mtime = time.time()
        self.client.update_entry(entry)
        # replaced chunks would otherwise sit on volume servers forever
        # (every open(.., 'w') rewrite truncates first)
        self._queue_deletion_quiet(old_chunks)

    def _queue_deletion_quiet(self, chunks):
        """Best-effort chunk-deletion queueing from error/cleanup paths: a
        filer hiccup here must not mask the original failure."""
        if not chunks:
            return
        try:
            self.client.queue_chunk_deletion(chunks)
        except Exception:
            pass

    def flush(self, path, fi):
        return self._flush_handle(fi)

    def release(self, path, fi):
        out = self._flush_handle(fi)
        self.handles.pop(fi.contents.fh, None)
        return out

    def _flush_handle(self, fi):
        h = self.handles.get(fi.contents.fh)
        if h is None:
            return 0
        return self._do_flush(h)

    def _do_flush(self, h: "_Handle"):
        if (not h.dirty.intervals and not h.pending_chunks
                and h.new_size is None):
            return 0
        # re-fetch: another writer may have updated the entry meanwhile
        try:
            entry = self.client.find_entry(h.entry.full_path)
        except (NotFoundError, HttpError):
            entry = h.entry
        moved_pending = []
        if h.pending_chunks:
            moved_pending = h.pending_chunks
            h.pending_chunks = []
        runs = h.dirty.pop_all()
        new_chunks: list = []
        for idx, (run_offset, data) in enumerate(runs):
            landed: list = []
            try:
                chunks, _ = split_and_upload(
                    self.master_url, data, entry.name, self.chunk_size,
                    collection=self.collection,
                    replication=self.replication, uploaded=landed)
            except Exception:
                # nothing is lost: every popped run (finished or not) goes
                # back into the dirty buffer and the spilled chunks back to
                # pending, so a retried flush re-uploads from scratch;
                # fids that already landed are queued for deletion
                h.pending_chunks = moved_pending
                for off2, data2 in runs:
                    h.dirty.add(off2, data2)
                self._queue_deletion_quiet(new_chunks + landed)
                raise
            for c in chunks:
                c.offset += run_offset
            new_chunks.extend(chunks)
        entry.chunks = list(entry.chunks) + moved_pending + new_chunks
        entry.attr.mtime = time.time()
        try:
            self.client.update_entry(entry)
        except (NotFoundError, HttpError):
            self.client.create_entry(entry)
        h.entry = entry
        h.new_size = None
        return 0
