"""S3-Select-style JSON query engine (reference weed/query/)."""

from .json_query import QueryError, parse_query, query_json_lines  # noqa: F401
