"""SELECT ... FROM ... WHERE ... over JSON documents.

Reference weed/query/json/query_json.go + weed/query/sqltypes/ (the
volume server's S3-Select-ish `Query` RPC, volume_grpc_query.go:12):
each needle holds JSON documents (one per line); the query projects
fields (dotted paths) and filters rows. Supported grammar, matching the
reference's WIP subset:

    SELECT * | field[,field...] FROM <anything>
        [WHERE <cond> [AND|OR <cond>]...]
    cond := path (=|!=|<|<=|>|>=) literal
    literal := 'string' | "string" | number | true | false | null
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, Dict, List, Optional


class QueryError(Exception):
    pass


_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*") |
        (?P<num>-?\d+(?:\.\d+)?) |
        (?P<op><=|>=|!=|=|<|>) |
        (?P<word>[A-Za-z_][\w.*]*|\*) |
        (?P<comma>,)
    )""", re.VERBOSE)


def _tokenize(s: str) -> List[tuple]:
    out, pos = [], 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if m is None:
            if s[pos:].strip() == "":
                break
            raise QueryError(f"bad token at {s[pos:pos + 20]!r}")
        pos = m.end()
        for kind in ("str", "num", "op", "word", "comma"):
            if m.group(kind) is not None:
                out.append((kind, m.group(kind)))
                break
    return out


def _get_path(doc: Any, path: str):
    cur = doc
    for part in path.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return None
    return cur


def _literal(kind: str, text: str):
    if kind == "str":
        return text[1:-1].replace("\\'", "'").replace('\\"', '"')
    if kind == "num":
        return float(text) if "." in text else int(text)
    low = text.lower()
    if low == "true":
        return True
    if low == "false":
        return False
    if low == "null":
        return None
    raise QueryError(f"bad literal {text!r}")


_OPS: Dict[str, Callable[[Any, Any], bool]] = {
    "=": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a is not None and b is not None and a < b,
    "<=": lambda a, b: a is not None and b is not None and a <= b,
    ">": lambda a, b: a is not None and b is not None and a > b,
    ">=": lambda a, b: a is not None and b is not None and a >= b,
}


class Query:
    def __init__(self, projections: List[str], where):
        self.projections = projections      # ["*"] or dotted paths
        self.where = where                  # None or predicate(doc)

    def match(self, doc) -> bool:
        return self.where is None or self.where(doc)

    def project(self, doc):
        if self.projections == ["*"]:
            return doc
        out = {}
        for p in self.projections:
            v = _get_path(doc, p)
            if v is not None:
                # nested output keyed by the last path segment,
                # matching the reference's flattened projection
                out[p.split(".")[-1]] = v
        return out


def parse_query(sql: str) -> Query:
    toks = _tokenize(sql)
    i = 0

    def expect_word(word: str):
        nonlocal i
        if i >= len(toks) or toks[i][0] != "word" or \
                toks[i][1].upper() != word:
            raise QueryError(f"expected {word}")
        i += 1

    expect_word("SELECT")
    projections: List[str] = []
    while i < len(toks):
        kind, text = toks[i]
        if kind == "word" and text.upper() == "FROM":
            break
        if kind == "word":
            projections.append(text)
            i += 1
        elif kind == "comma":
            i += 1
        else:
            raise QueryError(f"bad projection {text!r}")
    if not projections:
        raise QueryError("no projections")
    if "*" in projections:
        projections = ["*"]
    expect_word("FROM")
    if i < len(toks) and toks[i][0] == "word":
        i += 1                              # table name is decorative
    where = None
    if i < len(toks):
        expect_word("WHERE")
        conds: List[tuple] = []             # (joiner, pred)
        joiner = None
        while i < len(toks):
            if toks[i][0] != "word":
                raise QueryError("expected field path")
            path = toks[i][1]
            i += 1
            if i >= len(toks) or toks[i][0] != "op":
                raise QueryError("expected comparison operator")
            op = _OPS[toks[i][1]]
            i += 1
            if i >= len(toks) or toks[i][0] not in ("str", "num",
                                                    "word"):
                raise QueryError("expected literal")
            lit = _literal(toks[i][0], toks[i][1])
            i += 1
            conds.append((joiner,
                          lambda d, p=path, o=op, v=lit:
                          o(_get_path(d, p), v)))
            if i < len(toks) and toks[i][0] == "word" and \
                    toks[i][1].upper() in ("AND", "OR"):
                joiner = toks[i][1].upper()
                i += 1
            else:
                break
        if not conds:
            raise QueryError("empty WHERE clause")
        if i < len(toks):
            raise QueryError(f"trailing tokens at {toks[i][1]!r}")

        def predicate(doc) -> bool:
            result = conds[0][1](doc)
            for join, pred in conds[1:]:
                if join == "AND":
                    result = result and pred(doc)
                else:
                    result = result or pred(doc)
            return result
        where = predicate
    return Query(projections, where)


def query_json_lines(data: bytes, sql: str,
                     limit: int = 0) -> List[dict]:
    """Run a query over newline-delimited JSON documents (or a single
    JSON document / top-level array). Returns projected rows."""
    q = parse_query(sql)
    rows: List[dict] = []
    text = data.decode("utf-8", "replace").strip()
    docs = []
    if text.startswith("["):
        try:
            docs = json.loads(text)
        except ValueError as e:
            raise QueryError(f"bad JSON array: {e}") from None
    else:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                docs.append(json.loads(line))
            except ValueError:
                continue                    # skip non-JSON lines
    for doc in docs:
        if q.match(doc):
            rows.append(q.project(doc))
            if limit and len(rows) >= limit:
                break
    return rows
