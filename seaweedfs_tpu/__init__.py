"""seaweedfs_tpu — a TPU-native re-design of SeaweedFS.

A distributed object store / file system (Facebook Haystack + f4 designs)
whose performance-critical erasure-coding pipeline runs on TPU:
the Reed-Solomon GF(2^8) encode/reconstruct — a SIMD assembly loop in the
reference (klauspost/reedsolomon) — is re-built as a batched GF(2) bit-plane
matmul on the MXU via JAX/XLA/Pallas, with a C++ native codec as the CPU
fallback and a numpy reference for conformance.

Reference: CodeLingoBot/seaweedfs @ /root/reference (Go, v1.71).
This is NOT a port; architecture is TPU-first (see SURVEY.md §7).
"""

VERSION = "0.1.0"
