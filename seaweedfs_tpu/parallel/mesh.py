"""Device mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axis_names: Sequence[str] = ("data", "shard"),
              devices=None):
    """Build a Mesh over the available devices.

    Default layout: as many devices as possible on the 'data' (stripe) axis
    with the 'shard' axis sized 2 when the device count is even — encode is
    embarrassingly parallel over stripes, so 'data' gets the bulk; 'shard'
    exists to exercise output-sharding + psum paths (and maps to real
    multi-host topologies where shard files live on different hosts).
    """
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if shape is None:
        if n % 2 == 0 and n > 1:
            shape = (n // 2, 2)
        else:
            shape = (n, 1)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names=tuple(axis_names[: len(shape)]))


def make_codec_mesh(devices=None, width_devices: Optional[int] = None):
    """Mesh for MeshCodec dispatches: EVERY device on the 'data'
    (stripe-width) axis.

    The default make_mesh layout reserves half the devices for the
    'shard' axis (output sharding / psum paths), which is right for the
    distributed-rebuild programs but halves the width parallelism of a
    codec dispatch — the payload axis is the only one a plain
    encode/decode matmul shards over, so a (4, 2) mesh left 4 of 8
    devices idle on every MeshCodec call. Width is capped by
    SW_EC_MESH_WIDTH_DEVICES (0 = all visible devices).
    """
    import jax
    from ..util import config

    devices = list(devices if devices is not None else jax.devices())
    cap = (int(width_devices) if width_devices is not None
           else config.env_int("SW_EC_MESH_WIDTH_DEVICES"))
    width = len(devices) if cap <= 0 else min(cap, len(devices))
    return make_mesh(shape=(width, 1), axis_names=("data", "shard"),
                     devices=devices[:width])
