"""Device mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axis_names: Sequence[str] = ("data", "shard"),
              devices=None):
    """Build a Mesh over the available devices.

    Default layout: as many devices as possible on the 'data' (stripe) axis
    with the 'shard' axis sized 2 when the device count is even — encode is
    embarrassingly parallel over stripes, so 'data' gets the bulk; 'shard'
    exists to exercise output-sharding + psum paths (and maps to real
    multi-host topologies where shard files live on different hosts).
    """
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if shape is None:
        if n % 2 == 0 and n > 1:
            shape = (n // 2, 2)
        else:
            shape = (n, 1)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names=tuple(axis_names[: len(shape)]))
