"""Multi-chip erasure coding: SPMD GF(2) matmuls over a device mesh.

Two sharding strategies compose (the EC analogs of DP/TP — SURVEY §2.6):

  * encode — stripes are independent byte positions, so the payload axis n
    shards over 'data' (pure data parallel, zero communication), while the
    parity-output bit-rows shard over 'shard' (output/tensor parallel; the
    input is replicated across that axis by GSPMD). One jit, XLA inserts
    the layout.

  * rebuild — the contraction (input bit-rows of surviving shards) shards
    over 'shard': each device holds a slice of the surviving shards, computes
    its partial GF(2) products, and the XOR-reduction completes with a
    lax.psum over ICI followed by mod 2. This is the device-level analog of
    the reference's reconstruct-on-read gathering >=10 sibling shards over
    gRPC (reference store_ec.go:319-373).

All arithmetic is exact int32; results are bit-identical to the single-chip
and CPU backends.
"""

from __future__ import annotations

import functools

import numpy as np

from ..ops import device_stats, gf256


def _pad_rows(mat: np.ndarray, mult: int) -> np.ndarray:
    rows = mat.shape[0]
    pad = (-rows) % mult
    if pad == 0:
        return mat
    return np.concatenate(
        [mat, np.zeros((pad, mat.shape[1]), dtype=mat.dtype)], axis=0)


def encode_in_specs(mesh, m: int):
    """The PartitionSpecs sharded_encode_fn declares for its inputs
    (bitmat, data). Multi-process callers must BUILD their global
    arrays with exactly these (jit refuses mismatched committed inputs
    across processes) — one definition, used by both sides."""
    from jax.sharding import PartitionSpec as P
    bm_cols = "shard" if (m * 8) % mesh.shape["shard"] == 0 else None
    return P(None, bm_cols), P(None, "data")


def rebuild_in_specs(mesh):
    """PartitionSpecs for sharded_rebuild_fn's (bitmat_dec, survivors)."""
    from jax.sharding import PartitionSpec as P
    return P("shard", None), P(None, "data")


def sharded_encode_fn(mesh, k: int, m: int, n: int):
    """Returns (jitted_fn, bitmat) for distributed encode.

    jitted_fn(bitmat (k*8, m*8) int8, data (k, n) uint8) -> parity (m, n),
    with n sharded over 'data' and the parity rows over 'shard'.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def fn(bitmat, data):
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = ((data[:, None, :] >> shifts[None, :, None]) & 1)
        x = bits.reshape(k * 8, n).astype(jnp.int8)
        y = jax.lax.dot_general(
            bitmat.T, x, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        ybits = (y & 1).astype(jnp.uint8).reshape(m, 8, n)
        weights = (jnp.uint8(1) << shifts)[None, :, None]
        return (ybits * weights).sum(axis=1, dtype=jnp.uint8)

    bitmat = gf256.bit_matrix(
        gf256.build_matrix(k, k + m)[k:]).astype(np.int8)
    # parity rows shard over 'shard' only when they divide evenly; otherwise
    # the output replicates across that axis (the matmul itself still
    # partitions over 'data')
    out_rows = "shard" if m % mesh.shape["shard"] == 0 else None
    bm_spec, data_spec = encode_in_specs(mesh, m)
    jfn = device_stats.wrap(
        jax.jit(
            fn,
            in_shardings=(NamedSharding(mesh, bm_spec),
                          NamedSharding(mesh, data_spec)),
            out_shardings=NamedSharding(mesh, P(out_rows, "data"))),
        "sharded_ec.encode_fn")
    return jfn, bitmat


def sharded_rebuild_fn(mesh, k: int, n_out_shards: int, n: int):
    """Returns jitted_fn for distributed reconstruct with explicit psum.

    jitted_fn(bitmat_dec (k*8p, out*8) int8 sharded over 'shard' on axis 0,
              survivors (k, n) uint8 sharded ('shard' on rows, 'data' on n))
      -> rebuilt (n_out_shards, n) uint8, n sharded over 'data'.

    k*8 is zero-padded so the contraction axis splits evenly over 'shard';
    zero rows contribute nothing to the XOR.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    shard_ax = mesh.shape["shard"]
    k8p = k * 8 + ((-k * 8) % shard_ax)
    out8 = n_out_shards * 8

    def local(bm_local, bits_local):
        # bm_local (k8p/s, out8), bits_local (k8p/s, n/d)
        y = jax.lax.dot_general(
            bm_local.T, bits_local,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        y = jax.lax.psum(y, "shard")
        shifts = jnp.arange(8, dtype=jnp.uint8)
        ybits = (y & 1).astype(jnp.uint8).reshape(n_out_shards, 8, -1)
        weights = (jnp.uint8(1) << shifts)[None, :, None]
        return (ybits * weights).sum(axis=1, dtype=jnp.uint8)

    # jax.shard_map only exists from 0.5; fall back to the experimental
    # home it had before that — gated on the same capability probe the
    # DCN-tier test uses, so shim and test retire together
    from .multihost import has_native_shard_map
    if has_native_shard_map():
        shard_map = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map
    smap = shard_map(
        local, mesh=mesh,
        in_specs=(P("shard", None), P("shard", "data")),
        out_specs=P(None, "data"))

    def fn(bitmat_dec, survivors):
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = ((survivors[:, None, :] >> shifts[None, :, None]) & 1)
        x = bits.reshape(k * 8, n).astype(jnp.int8)
        x = jnp.pad(x, ((0, k8p - k * 8), (0, 0)))
        return smap(bitmat_dec, x)

    bm_spec, surv_spec = rebuild_in_specs(mesh)
    return device_stats.wrap(
        jax.jit(
            fn,
            in_shardings=(NamedSharding(mesh, bm_spec),
                          NamedSharding(mesh, surv_spec)),
            out_shardings=NamedSharding(mesh, P(None, "data"))),
        "sharded_ec.rebuild_fn")


def decode_bitmat(k: int, m: int, survivor_rows, missing_rows,
                  pad_to_mult: int = 1) -> np.ndarray:
    """GF(2) lift of the decode matrix restoring missing_rows from the first
    k survivor_rows, zero-padded on the contraction axis to pad_to_mult.
    The coefficient derivation is the shared fused decode plan
    (gf256.decode_coeff_rows — same rows ReedSolomonCodec.decode_plan
    and rebuild_ec_files dispatch in one matmul)."""
    matrix = gf256.build_matrix(k, k + m)
    coeffs = gf256.decode_coeff_rows(matrix, k, survivor_rows,
                                     missing_rows)  # (len(missing), k)
    bm = gf256.bit_matrix(coeffs).astype(np.int8)  # (k*8, len(missing)*8)
    return _pad_rows(bm, pad_to_mult)


def distributed_ec_step(mesh, k: int = 10, m: int = 4,
                        n_per_device: int = 2048):
    """One full distributed EC 'training step' for dry-runs: encode a
    sharded payload, drop m shards, rebuild them with the psum path, and
    return (parity, rebuilt, max_abs_diff_vs_encode).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    data_ax = mesh.shape["data"]
    shard_ax = mesh.shape["shard"]
    n = n_per_device * data_ax

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (k, n), dtype=np.uint8)

    enc_fn, enc_bitmat = sharded_encode_fn(mesh, k, m, n)
    parity = enc_fn(jnp.asarray(enc_bitmat), jnp.asarray(data))

    # drop the last m data shards; reconstruct them from the first k
    # survivors (k-m data shards + m parity shards)
    survivors = list(range(k - m)) + list(range(k, k + m))
    missing = list(range(k - m, k))
    reb_fn = sharded_rebuild_fn(mesh, k, len(missing), n)
    bm_dec = decode_bitmat(k, m, survivors, missing, pad_to_mult=shard_ax)
    surv_data = np.concatenate(
        [data[: k - m], np.asarray(parity)], axis=0)  # (k, n)
    rebuilt = reb_fn(jnp.asarray(bm_dec), jnp.asarray(surv_data))

    diff = int(np.abs(np.asarray(rebuilt).astype(np.int32)
                      - data[k - m: k].astype(np.int32)).max())
    return np.asarray(parity), np.asarray(rebuilt), diff
