"""Multi-host device tier: one logical mesh over DCN-connected
processes (SURVEY §5.8).

The reference scales EC work across hosts by fanning shard jobs over
the cluster (reference weed/shell/command_ec_rebuild.go:57-240 — each
rebuild runs whole on one server). The TPU-native design instead forms
ONE `jax.sharding.Mesh` spanning every process's devices
(`jax.distributed.initialize`): intra-host axes ride ICI, cross-host
axes ride DCN, and the same `shard_map`/`psum` programs from
`sharded_ec.py` compile unchanged — XLA inserts the cross-host
collectives.

Wiring: `init_distributed()` before any other jax call (the CLI's
`-mesh.coordinator/-mesh.processes/-mesh.processId` volume flags call
it when set; tests drive it directly). Every process then sees the
GLOBAL device list and participates in every jit; inputs are built
per-process from local shards via `jax.make_array_from_callback`, and
results are checked against the process-local oracle shardwise —
no host ever materializes another host's bytes.

Validated by tests/test_multihost.py: 2 processes x 4 virtual CPU
devices each form an 8-device mesh and run the full encode + psum
rebuild step (`multihost_ec_step`), bit-checked per process against
the NumpyCodec oracle.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def jax_version() -> Tuple[int, int]:
    """(major, minor) of the installed jax, (0, 0) when unparsable."""
    import jax
    parts = str(jax.__version__).split(".")
    try:
        return int(parts[0]), int(parts[1])
    except (IndexError, ValueError):
        return (0, 0)


def has_native_shard_map() -> bool:
    """`jax.shard_map` reached the top-level namespace with the 0.5
    line; before that it lives at jax.experimental.shard_map. The
    sharded_ec compat shim and the DCN-tier test gate on the same
    probe so they flip together when the image's jax moves."""
    import jax
    return hasattr(jax, "shard_map")


def multihost_cpu_capability() -> Tuple[bool, str]:
    """Can THIS jax build run multi-process collectives on the CPU
    backend? jax < 0.5 initializes the distributed service but every
    cross-process collective fails with \"collectives aren't
    implemented on the CPU backend\" — the capability arrived with the
    0.5-era CPU collectives implementation. Returns (ok, reason):
    reason explains a False verdict."""
    try:
        import jax
    except Exception as e:  # noqa: BLE001 - report, don't raise
        return False, f"jax unavailable: {e!r}"
    v = jax_version()
    if v < (0, 5):
        return False, (f"jax {jax.__version__} has no multiprocess CPU "
                       f"collectives (needs >= 0.5)")
    if not hasattr(jax, "distributed"):
        return False, "jax.distributed unavailable in this build"
    return True, ""


def init_distributed(coordinator_address: str, num_processes: int,
                     process_id: int,
                     local_device_ids: Optional[list] = None) -> None:
    """`jax.distributed.initialize` with the arguments the CLI flags
    carry. Must run before the first jax device query in the process;
    afterwards jax.devices() is the GLOBAL list and
    jax.local_devices() this host's slice."""
    import jax
    kwargs = {}
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id, **kwargs)


def _global(mesh, spec, value: np.ndarray):
    """A global Array with exactly `spec`, built from per-process
    local slices (every process holds the same host value, so each
    callback serves its addressable shards locally — no cross-host
    bytes move). Multi-process jit REQUIRES inputs to arrive already
    in the in_shardings layout."""
    import jax
    from jax.sharding import NamedSharding
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(value.shape, sharding,
                                        lambda idx: value[idx])


def multihost_ec_step(k: int = 10, m: int = 4,
                      n_per_device: int = 512) -> dict:
    """The distributed EC step (encode, drop m shards, psum-rebuild)
    on the GLOBAL mesh, inputs assembled per-process and outputs
    verified per-process against the CPU oracle. Returns a summary
    dict (identical on every process when everything agrees)."""
    import jax
    from ..ops.codec import NumpyCodec
    from .mesh import make_mesh
    from .sharded_ec import (decode_bitmat, sharded_encode_fn,
                             sharded_rebuild_fn)

    devices = jax.devices()
    mesh = make_mesh(devices=devices)
    n = n_per_device * mesh.shape["data"]

    # identical on every process: the logical payload
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (k, n), dtype=np.uint8)
    oracle = NumpyCodec(k, m).encode(data)

    from .sharded_ec import encode_in_specs, rebuild_in_specs

    enc_fn, bitmat = sharded_encode_fn(mesh, k, m, n)
    # the SAME spec objects the jit declares (one definition — a
    # drifted copy here would fail every multi-process run while
    # single-process tests kept passing)
    bm_spec, data_spec = encode_in_specs(mesh, m)
    parity = enc_fn(_global(mesh, bm_spec, bitmat.astype(np.int8)),
                    _global(mesh, data_spec, data))

    def check_local(global_arr, want: np.ndarray, label: str) -> int:
        """Compare only this process's addressable shards."""
        checked = 0
        for shard in global_arr.addressable_shards:
            got = np.asarray(shard.data)
            if not np.array_equal(got, want[shard.index]):
                raise AssertionError(
                    f"{label}: process {jax.process_index()} shard "
                    f"{shard.index} diverged from the oracle")
            checked += 1
        return checked

    parity_shards = check_local(parity, oracle, "multihost encode")

    survivors = list(range(k - m)) + list(range(k, k + m))
    missing = list(range(k - m, k))
    reb_fn = sharded_rebuild_fn(mesh, k, len(missing), n)
    bm_dec = decode_bitmat(k, m, survivors, missing,
                           pad_to_mult=mesh.shape["shard"])
    surv = np.concatenate([data[: k - m], oracle], axis=0)
    rb_bm_spec, rb_surv_spec = rebuild_in_specs(mesh)
    rebuilt = reb_fn(_global(mesh, rb_bm_spec, bm_dec.astype(np.int8)),
                     _global(mesh, rb_surv_spec, surv))
    rebuilt_shards = check_local(rebuilt, data[k - m: k],
                                 "multihost rebuild")

    return {
        "process_index": int(jax.process_index()),
        "process_count": int(jax.process_count()),
        "global_devices": len(devices),
        "local_devices": len(jax.local_devices()),
        "mesh_shape": dict(mesh.shape),
        "parity_shards_checked": parity_shards,
        "rebuilt_shards_checked": rebuilt_shards,
        "ok": True,
    }
