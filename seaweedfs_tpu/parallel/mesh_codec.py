"""MeshCodec — multi-chip EC as a first-class codec backend.

`-ec.backend mesh` runs every GF(2^8) coding matmul SPMD over a
`jax.sharding.Mesh` of all visible devices: the payload axis shards
over 'data' (stripes are independent byte positions — zero
communication), coefficients replicate, and XLA partitions the
bit-plane matmul (parallel/sharded_ec.py documents the math). On an
8-chip host a volume encode therefore streams through all chips from
the same `write_ec_files` call sites the single-chip TpuCodec uses;
on the CPU test mesh it exercises the identical program. Outputs are
bit-identical to every other backend (exact int32 arithmetic).

This is the serving-path face of SURVEY §2.6's device tier: the same
sharded programs the driver dry-runs via __graft_entry__ become the
volume server's encode/rebuild engine.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ..ops import gf256
from ..ops.codec import ReedSolomonCodec
from .mesh import make_mesh


class MeshCodec(ReedSolomonCodec):
    backend = "mesh"

    def __init__(self, data_shards: int, parity_shards: int,
                 matrix_kind: str = "vandermonde", mesh=None,
                 chunk_bytes: int = 32 << 20):
        super().__init__(data_shards, parity_shards, matrix_kind)
        self.chunk_bytes = int(chunk_bytes)
        self._mesh = mesh  # lazy: devices may not be initialized yet
        self._fns: Dict[Tuple[int, int, int], object] = {}

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = make_mesh()
        return self._mesh

    def _fn(self, rows_in: int, rows_out: int, n: int):
        """Jitted (bitmat (rows_in*8, rows_out*8) int8, data
        (rows_in, n) uint8) -> (rows_out, n) uint8, payload sharded
        over 'data'."""
        key = (rows_in, rows_out, n)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        def program(bitmat, data):
            shifts = jnp.arange(8, dtype=jnp.uint8)
            bits = ((data[:, None, :] >> shifts[None, :, None]) & 1)
            x = bits.reshape(rows_in * 8, n).astype(jnp.int8)
            y = jax.lax.dot_general(
                bitmat.T, x, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            ybits = (y & 1).astype(jnp.uint8).reshape(rows_out, 8, n)
            weights = (jnp.uint8(1) << shifts)[None, :, None]
            return (ybits * weights).sum(axis=1, dtype=jnp.uint8)

        mesh = self.mesh
        fn = jax.jit(
            program,
            in_shardings=(NamedSharding(mesh, P(None, None)),
                          NamedSharding(mesh, P(None, "data"))),
            out_shardings=NamedSharding(mesh, P(None, "data")))
        self._fns[key] = fn
        return fn

    def _width_bucket(self, n: int) -> int:
        """Pad widths to power-of-two buckets (compile reuse), then up to
        a multiple of the 'data' axis so the shard split is even."""
        data_ax = self.mesh.shape["data"]
        bucket = min(max(512, 1 << (n - 1).bit_length()), self.chunk_bytes)
        bucket = max(bucket, n)  # chunk_bytes cap may undershoot n's chunk
        return bucket + (-bucket) % data_ax

    def _matmul(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        data = np.ascontiguousarray(data, dtype=np.uint8)
        r, k = coeffs.shape
        n = data.shape[1]
        if n == 0:
            return np.zeros((r, 0), dtype=np.uint8)
        bitmat = jnp.asarray(gf256.bit_matrix(coeffs).astype(np.int8))
        out = np.empty((r, n), dtype=np.uint8)
        step = self.chunk_bytes
        for off in range(0, n, step):
            end = min(off + step, n)
            w = end - off
            bucket = self._width_bucket(w)
            fn = self._fn(k, r, bucket)
            if w < bucket:  # zero-pad: GF-linear, so exact
                padded = np.zeros((k, bucket), dtype=np.uint8)
                padded[:, :w] = data[:, off:end]
            else:
                padded = data[:, off:end]
            out[:, off:end] = np.asarray(fn(bitmat, padded))[:, :w]
        return out
