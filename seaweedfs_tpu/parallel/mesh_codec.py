"""MeshCodec — multi-chip EC as a first-class codec backend.

`-ec.backend mesh` runs every GF(2^8) coding matmul SPMD over a
`jax.sharding.Mesh` of all visible devices: the payload axis shards
over 'data' (stripes are independent byte positions — zero
communication), coefficients replicate, and XLA partitions the
GF(2) program (parallel/sharded_ec.py documents the math). On an
8-chip host a volume encode therefore streams through all chips from
the same `write_ec_files` call sites the single-chip TpuCodec uses;
on the CPU test mesh it exercises the identical program. Outputs are
bit-identical to every other backend (exact integer arithmetic).

Two program forms, chosen by mesh platform (same split as
ops/rs_tpu.fn_and_bitmat):

  * TPU — the bit-plane int8 matmul: unpack to GF(2) bit rows, one
    MXU dot, pack. The MXU eats the 8x lift for free.
  * everything else (the virtual CPU test mesh) — packed AND/popcount:
    the k*8 contraction bits packed into uint32 words, each output bit
    a parity of popcounts. ~64x less arithmetic and no 8x intermediate;
    this is what turned the round-5 rebuild from 2 MB/s into a usable
    hot path on the CPU mesh.

Dispatch discipline (the round-5 lesson): coefficients are lifted and
uploaded ONCE per coefficient matrix (bounded LRU, ops/codec._ConstCache),
chunk dispatches are issued before any output is drained (JAX dispatch is
async — blocking np.asarray per chunk serializes compute against d2h),
and the pipelined encode/rebuild path streams slabs through device_fn()
with bounded in-flight depth (ops/pipeline.PipelinedMatmul).

Width discipline (the round-16 lesson): the codec mesh puts EVERY
device on the 'data' axis (mesh.make_codec_mesh — the default
(n/2, 2) layout exists for the psum rebuild programs and would idle
half the mesh here), slabs below the SW_EC_MESH_SHARD_MIN_BYTES
payload crossover keep the single-device kernel (sharding a
kilobyte-wide reconstruct pays partitioning overhead it can't
amortize), and every sharded put records its per-device byte landing
in ops/telemetry so a silent fall-back to width-1 dispatch is a
visible counter regression, not a 74 -> 2 MB/s surprise.

This is the serving-path face of SURVEY §2.6's device tier: the same
sharded programs the driver dry-runs via __graft_entry__ become the
volume server's encode/rebuild engine.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ..ops import device_stats, gf256
from ..ops.codec import ReedSolomonCodec, _ConstCache, small_dispatch_default
from ..ops.rs_tpu import width_bucket
from ..ops.telemetry import STATS
from ..util import config
from .mesh import make_codec_mesh


class MeshCodec(ReedSolomonCodec):
    backend = "mesh"

    def __init__(self, data_shards: int, parity_shards: int,
                 matrix_kind: str = "vandermonde", mesh=None,
                 chunk_bytes: int = 32 << 20,
                 small_dispatch_bytes: int = None,
                 mesh_shard_min_bytes: int = None):
        super().__init__(data_shards, parity_shards, matrix_kind)
        self.chunk_bytes = int(chunk_bytes)
        self._mesh = mesh  # lazy: devices may not be initialized yet
        self._fns: Dict[Tuple[int, int, int], object] = {}
        self.small_dispatch_bytes = (
            small_dispatch_default() if small_dispatch_bytes is None
            else int(small_dispatch_bytes))
        # payload bytes (k * width) below which a dispatch keeps the
        # single-device path: sharding a small slab pays partitioning
        # overhead on every device without enough columns to amortize it
        self.mesh_shard_min_bytes = (
            config.env_int("SW_EC_MESH_SHARD_MIN_BYTES")
            if mesh_shard_min_bytes is None else int(mesh_shard_min_bytes))
        self._consts = _ConstCache()

    @property
    def mesh(self):
        if self._mesh is None:
            # ALL devices on the width axis — the default make_mesh
            # (data, shard) = (n/2, 2) layout is for the psum rebuild
            # programs and would leave half the mesh idle here
            self._mesh = make_codec_mesh()
        return self._mesh

    def _on_tpu_mesh(self) -> bool:
        return self.mesh.devices.flat[0].platform == "tpu"

    def _fn(self, rows_in: int, rows_out: int, n: int):
        """Jitted (const, data (rows_in, n) uint8) -> (rows_out, n)
        uint8, payload sharded over 'data', const replicated. The const
        is the int8 bit-matrix (TPU mesh) or the packed uint32 bit-
        matrix (elsewhere) — _device_const builds the matching form."""
        key = (rows_in, rows_out, n)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._on_tpu_mesh():
            def program(bitmat, data):
                shifts = jnp.arange(8, dtype=jnp.uint8)
                bits = ((data[:, None, :] >> shifts[None, :, None]) & 1)
                x = bits.reshape(rows_in * 8, n).astype(jnp.int8)
                y = jax.lax.dot_general(
                    bitmat.T, x,
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                ybits = (y & 1).astype(jnp.uint8).reshape(rows_out, 8, n)
                weights = (jnp.uint8(1) << shifts)[None, :, None]
                return (ybits * weights).sum(axis=1, dtype=jnp.uint8)
        else:
            nw = (rows_in * 8 + 31) // 32

            def program(bmp, data):
                d32 = data.astype(jnp.uint32)
                words = []
                for wi in range(nw):
                    acc = jnp.zeros((n,), jnp.uint32)
                    for b in range(4):
                        j = wi * 4 + b
                        if j < rows_in:
                            acc = acc | (d32[j] << (8 * b))
                    words.append(acc)
                outs = []
                for i in range(rows_out):
                    byte = jnp.zeros((n,), jnp.uint32)
                    for bit in range(8):
                        col = i * 8 + bit
                        ones = jnp.zeros((n,), jnp.uint32)
                        for wi in range(nw):
                            ones = ones + jax.lax.population_count(
                                words[wi] & bmp[wi, col])
                        byte = byte | ((ones & 1) << bit)
                    outs.append(byte.astype(jnp.uint8))
                return jnp.stack(outs)

        mesh = self.mesh
        fn = device_stats.wrap(
            jax.jit(
                program,
                in_shardings=(NamedSharding(mesh, P(None, None)),
                              NamedSharding(mesh, P(None, "data"))),
                out_shardings=NamedSharding(mesh, P(None, "data"))),
            "mesh_codec._fn")
        self._fns[key] = fn
        return fn

    def _device_const(self, coeffs: np.ndarray):
        """Device-resident replicated coefficient constant — uploaded
        once per coefficient matrix, reused across every slab of a
        rebuild/encode (round-5 fix: re-lifting + re-uploading per call
        was most of the 2 MB/s)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def make():
            if self._on_tpu_mesh():
                host = gf256.bit_matrix(coeffs).astype(np.int8)
            else:
                host = gf256.pack_bit_matrix(coeffs)
            return jax.device_put(
                host, NamedSharding(self.mesh, P(None, None)))

        return self._consts.get((coeffs.tobytes(), "mesh"), make)

    def _put(self, data: np.ndarray):
        """Sharded h2d: the width axis splits over 'data', and the
        per-device landing is recorded so a silent fall-back to a
        width-1 dispatch is visible in telemetry, not just wall time."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        arr = jax.device_put(
            data, NamedSharding(self.mesh, P(None, "data")))
        STATS.add("mesh_dispatches")
        for shard in arr.addressable_shards:
            STATS.add_mesh_device_bytes(str(shard.device),
                                        shard.data.nbytes)
        return arr

    def _single_device_fn(self, coeffs: np.ndarray, width: int):
        """The current single-device path (fused Pallas on TPU, packed
        popcount XLA elsewhere — ops/rs_tpu.fn_and_bitmat), for
        dispatches too small to amortize mesh partitioning."""
        import jax.numpy as jnp
        from ..ops.rs_tpu import fn_and_bitmat
        fn, const_host = fn_and_bitmat(coeffs, width)
        const = self._consts.get((coeffs.tobytes(), "single"),
                                 lambda: jnp.asarray(const_host))
        return fn, const, jnp.asarray

    def device_fn(self, coeffs: np.ndarray, width: int):
        """Streaming hook for PipelinedMatmul: (fn, resident const,
        put). `width` must come from pipeline_width_bucket (even shard
        split over 'data'). Below the SW_EC_MESH_SHARD_MIN_BYTES
        payload crossover (k * width) the single-device kernel is
        returned instead of the sharded program."""
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        r, k = coeffs.shape
        if k * width < self.mesh_shard_min_bytes or \
                self.mesh.shape["data"] <= 1:
            return self._single_device_fn(coeffs, width)
        return self._fn(k, r, width), self._device_const(coeffs), self._put

    def drain_pieces(self, out_dev, w: int):
        """Host pieces of a device output in width order: list of
        (col_offset, (r, piece_w) np.ndarray) covering [0, w). Sharded
        outputs drain one piece per device shard — consumers (the
        spread sink's per-target workers, rebuild shard writes) start
        on the first device's stripes without staging the full slab on
        the host; single-device outputs come back as one piece."""
        shards = getattr(out_dev, "addressable_shards", None) or []
        by_off = {}
        for shard in shards:
            lo = shard.index[1].start or 0
            if lo >= w or lo in by_off:  # clip tail pad; dedupe replicas
                continue
            piece = np.asarray(shard.data)
            if lo + piece.shape[1] > w:
                piece = piece[:, : w - lo]
            by_off[lo] = piece
        if not by_off:
            full = np.asarray(out_dev)
            return [(0, full[:, :w] if full.shape[1] > w else full)]
        return sorted(by_off.items())

    def pipeline_width_bucket(self, n: int, cap: int) -> int:
        bucket = width_bucket(n, cap)
        return bucket + (-bucket) % self.mesh.shape["data"]

    def _width_bucket(self, n: int) -> int:
        """Pad widths to power-of-two buckets (compile reuse), then up to
        a multiple of the 'data' axis so the shard split is even."""
        data_ax = self.mesh.shape["data"]
        bucket = min(max(512, 1 << (n - 1).bit_length()), self.chunk_bytes)
        bucket = max(bucket, n)  # chunk_bytes cap may undershoot n's chunk
        return bucket + (-bucket) % data_ax

    def _matmul(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        data = np.ascontiguousarray(data, dtype=np.uint8)
        r, k = coeffs.shape
        n = data.shape[1]
        if n == 0:
            return np.zeros((r, 0), dtype=np.uint8)
        from ..util import tracing
        out = np.empty((r, n), dtype=np.uint8)
        step = self.chunk_bytes
        # dispatch all chunks, then drain: the async dispatches overlap
        # device compute with the d2h of earlier chunks
        pending = []
        with tracing.span("dispatch", backend="mesh", bytes=int(n * k)):
            for off in range(0, n, step):
                end = min(off + step, n)
                w = end - off
                bucket = self._width_bucket(w)
                fn, bitmat, put = self.device_fn(coeffs, bucket)
                if w < bucket:  # zero-pad: GF-linear, so exact
                    padded = np.zeros((k, bucket), dtype=np.uint8)
                    padded[:, :w] = data[:, off:end]
                else:
                    padded = data[:, off:end]
                STATS.add("dispatches")
                STATS.add("device_bytes", w * k)
                pending.append((off, end, fn(bitmat, put(padded))))
        with tracing.span("drain", backend="mesh", bytes=int(n * r)):
            for off, end, dev in pending:
                out[:, off:end] = np.asarray(dev)[:, : end - off]
        return out
