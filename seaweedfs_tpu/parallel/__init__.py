"""parallel — multi-chip EC compute over a jax.sharding.Mesh.

The reference scales EC work by fanning volumes across volume servers over
gRPC (SURVEY §2.6); the TPU-native equivalent adds a second, device-level
tier: stripes and shard outputs sharded over a ('data', 'shard') mesh with
XLA collectives over ICI (psum for the GF(2) XOR-reductions in distributed
rebuild), multi-host over DCN via the same mesh axes.
"""

from .mesh import make_mesh  # noqa: F401
from .multihost import init_distributed, multihost_ec_step  # noqa: F401
from .sharded_ec import (  # noqa: F401
    sharded_encode_fn, sharded_rebuild_fn, distributed_ec_step,
)
