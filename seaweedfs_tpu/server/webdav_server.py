"""WebDAV gateway over the filer.

Reference weed/server/webdav_server.go + weed/command/webdav.go (the
reference adapts golang.org/x/net/webdav's FileSystem interface onto
filer gRPC; here the DAV protocol is handled directly: OPTIONS,
PROPFIND depth 0/1, GET/HEAD with ranges, PUT, MKCOL, DELETE, MOVE,
COPY, and enforced class-2 LOCK/UNLOCK — exclusive write locks with
timeouts, refresh, and 423 on token-less mutation, the same subset
golang.org/x/net/webdav's in-memory LockSystem provides).

Works over an in-process `Filer` or a remote `FilerClient`.
"""

from __future__ import annotations

import posixpath
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from typing import Optional

from ..filer import Attr, Entry
from ..util.locks import make_lock
from ..filer.entry import new_dir_entry
from ..filer.filer import FilerError, NotFoundError
from ..filer.stream import read_chunked
from ..filer.upload import split_and_upload
from .http_util import (HttpError, HttpServer, Request, Response, Router)

DAV_NS = "DAV:"


def _rfc1123(ts: float) -> str:
    # formatdate, not strftime: day/month names must be English
    # regardless of LC_TIME — DAV clients parse Last-Modified
    import email.utils
    return email.utils.formatdate(ts, usegmt=True)


def _iso8601(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


class _Lock:
    __slots__ = ("token", "owner", "expires")

    def __init__(self, token: str, owner: str, expires: float):
        self.token = token
        self.owner = owner
        self.expires = expires


class LockManager:
    """In-memory exclusive write locks, depth-infinity (the shape
    golang.org/x/net/webdav's memLS implements and office clients use).
    A lock on a path covers the path and everything under it."""

    def __init__(self):
        self._locks: dict = {}       # path -> _Lock
        self._mu = make_lock("webdav_server._mu")

    def _evict_expired(self, now: float):
        dead = [p for p, lk in self._locks.items() if lk.expires <= now]
        for p in dead:
            del self._locks[p]

    def _covering(self, path: str):
        """(lock_path, lock) whose scope covers `path`, else None."""
        probe = path
        while True:
            lk = self._locks.get(probe)
            if lk is not None:
                return probe, lk
            if probe in ("/", ""):
                return None
            probe = posixpath.dirname(probe) or "/"

    def acquire(self, path: str, timeout_s: float, owner: str) -> str:
        now = time.time()
        with self._mu:
            self._evict_expired(now)
            hit = self._covering(path)
            if hit is not None:
                raise HttpError(423, f"locked by {hit[1].owner or 'peer'}")
            # a descendant lock also conflicts with an infinite-depth
            # request on the ancestor
            prefix = path.rstrip("/") + "/"
            if any(p.startswith(prefix) for p in self._locks):
                raise HttpError(423, "descendant is locked")
            token = f"opaquelocktoken:{uuid.uuid4()}"
            self._locks[path] = _Lock(token, owner, now + timeout_s)
            return token

    def refresh(self, path: str, if_header: str, timeout_s: float) -> str:
        now = time.time()
        with self._mu:
            self._evict_expired(now)
            hit = self._covering(path)
            if hit is None:
                raise HttpError(412, "no lock to refresh")
            if hit[1].token not in (if_header or ""):
                raise HttpError(412, "lock token mismatch")
            hit[1].expires = now + timeout_s
            return hit[1].token

    def release(self, path: str, token: str) -> bool:
        with self._mu:
            self._evict_expired(time.time())
            hit = self._covering(path)
            if hit is None or hit[1].token != token:
                return False
            del self._locks[hit[0]]
            return True

    def require(self, path: str, if_header: str,
                descendants: bool = False):
        """Raise 423 unless the lock covering `path` has its token in
        the If header (RFC4918 tagged-list parsing is simplified to a
        substring check, like many servers). With ``descendants=True``
        — for operations that destroy the subtree (DELETE, MOVE,
        overwriting COPY) — locks held below `path` must be presented
        too; a PROPPATCH/MKCOL on the parent doesn't touch them."""
        with self._mu:
            self._evict_expired(time.time())
            hit = self._covering(path)
            if hit is not None and hit[1].token not in (if_header or ""):
                raise HttpError(423, "resource is locked")
            if descendants:
                prefix = path.rstrip("/") + "/"
                for p, lk in self._locks.items():
                    if p.startswith(prefix) and \
                            lk.token not in (if_header or ""):
                        raise HttpError(423, f"{p} is locked")

    def forget(self, path: str):
        """Drop any lock at `path` or below — the resource was deleted
        or moved away (RFC4918 9.6)."""
        prefix = path.rstrip("/") + "/"
        with self._mu:
            for p in [p for p in self._locks
                      if p == path or p.startswith(prefix)]:
                del self._locks[p]


class WebDavServer:
    def __init__(self, filer, master_url: str,
                 port: int = 7333, host: str = "127.0.0.1",
                 chunk_size: int = 8 << 20,
                 collection: str = "", replication: str = "",
                 fetcher=None):
        self.filer = filer
        self.master_url = master_url
        self.chunk_size = chunk_size
        self.locks = LockManager()
        self.collection = collection
        self.replication = replication
        self._fetch = fetcher
        router = Router()
        router.set_fallback(self.dispatch)
        self.server = HttpServer(port, router, host)
        self.port = self.server.port
        self.host = host

    def start(self):
        self.server.start()
        return self

    def stop(self):
        self.server.stop()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- dispatch -----------------------------------------------------------

    def dispatch(self, req: Request):
        path = urllib.parse.unquote(req.path)
        if path != "/":
            path = posixpath.normpath(path)
        method = req.method
        if method == "OPTIONS":
            return Response(b"", 200, "text/plain", {
                "DAV": "1, 2",
                "MS-Author-Via": "DAV",
                "Allow": ("OPTIONS, GET, HEAD, PUT, DELETE, PROPFIND, "
                          "PROPPATCH, MKCOL, MOVE, COPY, LOCK, UNLOCK")})
        if method == "PROPFIND":
            return self.propfind(req, path)
        if method in ("GET", "HEAD"):
            return self.get(req, path)
        # class-2 enforcement: a mutating method on a locked resource
        # must present the lock token (If header) or draw 423; subtree-
        # destroying operations must also present descendant locks
        if_header = req.headers.get("If", "")
        if method in ("PUT", "MKCOL", "PROPPATCH"):
            self.locks.require(path, if_header)
        if method == "DELETE":
            self.locks.require(path, if_header, descendants=True)
        if method in ("MOVE", "COPY"):
            if method == "MOVE":
                self.locks.require(path, if_header, descendants=True)
            dest = self._dest_path(req)
            if dest:
                # an overwriting MOVE/COPY replaces the dest subtree
                self.locks.require(dest, if_header, descendants=True)
        if method == "PUT":
            return self.put(req, path)
        if method == "MKCOL":
            return self.mkcol(req, path)
        if method == "DELETE":
            return self.delete(req, path)
        if method in ("MOVE", "COPY"):
            return self.move_copy(req, path, copy=(method == "COPY"))
        if method == "PROPPATCH":
            return self._multistatus([self._prop_response(
                path, None, ok_props_only=True)])
        if method == "LOCK":
            return self.lock(req, path)
        if method == "UNLOCK":
            return self.unlock(req, path)
        raise HttpError(405, method)

    # -- handlers -----------------------------------------------------------

    def propfind(self, req: Request, path: str):
        depth = req.headers.get("Depth", "1")
        try:
            entry = self.filer.find_entry(path)
        except NotFoundError:
            raise HttpError(404, path) from None
        responses = [self._prop_response(path, entry)]
        if depth != "0" and entry.is_directory:
            for child in self.filer.list_entries(path, limit=10000):
                responses.append(
                    self._prop_response(child.full_path, child))
        return self._multistatus(responses)

    def get(self, req: Request, path: str):
        try:
            entry = self.filer.find_entry(path)
        except NotFoundError:
            raise HttpError(404, path) from None
        if entry.is_directory:
            names = [e.name + ("/" if e.is_directory else "")
                     for e in self.filer.list_entries(path, limit=10000)]
            body = ("\n".join(names) + "\n").encode()
            return Response(body, 200, "text/plain")
        size = entry.size()
        offset, length, status = 0, size, 200
        headers = {"Accept-Ranges": "bytes",
                   "Last-Modified": _rfc1123(entry.attr.mtime)}
        from .http_util import parse_range
        parsed = parse_range(req.headers.get("Range", ""), size)
        if parsed is not None:
            offset, length = parsed
            headers["Content-Range"] = \
                f"bytes {offset}-{offset + length - 1}/{size}"
            status = 206
        head = req.method == "HEAD"
        body = b"" if head else read_chunked(
            entry.chunks, offset, length, self._chunk_fetcher())
        return Response(body, status,
                        entry.attr.mime or "application/octet-stream",
                        headers, content_length=length if head else None)

    def put(self, req: Request, path: str):
        data = req.body
        existed = self.filer.exists(path)
        chunks, md5_hex = split_and_upload(
            self.master_url, data, posixpath.basename(path),
            self.chunk_size, collection=self.collection,
            replication=self.replication,
            content_type=req.headers.get("Content-Type",
                                         "application/octet-stream"))
        now = time.time()
        attr = Attr(mtime=now, crtime=now,
                    mime=req.headers.get("Content-Type", ""),
                    collection=self.collection,
                    replication=self.replication, md5=md5_hex)
        self.filer.create_entry(Entry(full_path=path, attr=attr,
                                      chunks=chunks))
        return Response(b"", 201 if not existed else 204)

    def mkcol(self, req: Request, path: str):
        if self.filer.exists(path):
            raise HttpError(405, f"{path} exists")
        self.filer.create_entry(new_dir_entry(path))
        return Response(b"", 201)

    def delete(self, req: Request, path: str):
        try:
            self.filer.delete_entry(path, recursive=True,
                                    ignore_recursive_error=True)
        except NotFoundError:
            raise HttpError(404, path) from None
        # RFC4918 9.6: DELETE removes locks on the deleted resource —
        # otherwise the path stays 423 for the rest of the lock timeout
        self.locks.forget(path)
        return Response(b"", 204)

    def move_copy(self, req: Request, path: str, copy: bool):
        dest = self._dest_path(req)
        if not dest:
            raise HttpError(400, "missing Destination header")
        overwrite = req.headers.get("Overwrite", "T").upper() != "F"
        try:
            self.filer.find_entry(path)  # 404 before touching the dest
        except NotFoundError:
            raise HttpError(404, path) from None
        if dest == path or dest.startswith(path + "/"):
            raise HttpError(409, "destination inside source")
        dest_existed = self.filer.exists(dest)
        if dest_existed and not overwrite:
            raise HttpError(412, f"{dest} exists")
        try:
            if copy:
                self._copy_tree(path, dest)
            else:
                if dest_existed:
                    self.filer.delete_entry(dest, recursive=True,
                                            ignore_recursive_error=True)
                self.filer.rename_entry(path, dest)
        except NotFoundError:
            raise HttpError(404, path) from None
        except FilerError as e:
            raise HttpError(409, str(e)) from None
        if not copy:
            # the source no longer exists: its lock goes with it
            self.locks.forget(path)
        return Response(b"", 204 if dest_existed else 201)

    @staticmethod
    def _dest_path(req: Request) -> str:
        dest_header = req.headers.get("Destination", "")
        if not dest_header:
            return ""
        return posixpath.normpath(urllib.parse.unquote(
            urllib.parse.urlparse(dest_header).path))

    @staticmethod
    def _parse_timeout(header: str) -> float:
        """'Second-N', 'Infinite', or comma list — first parsable wins
        (RFC4918 10.7); capped like golang webdav's maxTimeout."""
        for part in (header or "").split(","):
            part = part.strip()
            if part.lower().startswith("second-"):
                try:
                    return min(float(part[7:]), 7 * 24 * 3600.0)
                except ValueError:
                    continue
            if part.lower() == "infinite":
                return 7 * 24 * 3600.0
        return 3600.0

    def lock(self, req: Request, path: str):
        timeout = self._parse_timeout(req.headers.get("Timeout", ""))
        owner = ""
        body = req.body
        if body:
            try:
                owner_el = ET.fromstring(body).find(
                    "{%s}owner" % DAV_NS)
                if owner_el is not None:
                    owner = "".join(owner_el.itertext()).strip()
            except ET.ParseError:
                raise HttpError(400, "malformed lock body") from None
            token = self.locks.acquire(path, timeout, owner)
        else:
            # bodyless LOCK = refresh of the token in the If header
            token = self.locks.refresh(
                path, req.headers.get("If", ""), timeout)
        ns = "{%s}" % DAV_NS
        root = ET.Element(ns + "prop")
        disc = ET.SubElement(root, ns + "lockdiscovery")
        active = ET.SubElement(disc, ns + "activelock")
        ET.SubElement(ET.SubElement(active, ns + "locktype"),
                      ns + "write")
        ET.SubElement(ET.SubElement(active, ns + "lockscope"),
                      ns + "exclusive")
        ET.SubElement(active, ns + "depth").text = "infinity"
        ET.SubElement(active, ns + "timeout").text = \
            f"Second-{int(timeout)}"
        if owner:
            ET.SubElement(active, ns + "owner").text = owner
        ET.SubElement(ET.SubElement(active, ns + "locktoken"),
                      ns + "href").text = token
        body = b'<?xml version="1.0" encoding="utf-8"?>' + \
            ET.tostring(root)
        return Response(body, 200, "application/xml",
                        {"Lock-Token": f"<{token}>"})

    def unlock(self, req: Request, path: str):
        header = req.headers.get("Lock-Token", "").strip()
        token = header.strip("<>")
        if not token:
            raise HttpError(400, "missing Lock-Token header")
        if not self.locks.release(path, token):
            raise HttpError(409, "no such lock")
        return Response(b"", 204)

    # -- helpers ------------------------------------------------------------

    def _copy_tree(self, src: str, dest: str):
        """COPY re-uploads file bytes (chunks are owned by exactly one
        entry — sharing them would double-free on delete; the reference
        webdav does a read/write copy too)."""
        entry = self.filer.find_entry(src)
        if entry.is_directory:
            if not self.filer.exists(dest):
                self.filer.create_entry(new_dir_entry(dest))
            for child in self.filer.list_entries(src, limit=10000):
                self._copy_tree(child.full_path,
                                posixpath.join(dest, child.name))
            return
        data = read_chunked(entry.chunks, 0, entry.size(),
                            self._chunk_fetcher())
        chunks, md5_hex = split_and_upload(
            self.master_url, data, posixpath.basename(dest),
            self.chunk_size, collection=self.collection,
            replication=self.replication,
            content_type=entry.attr.mime or "application/octet-stream")
        now = time.time()
        attr = Attr(mtime=now, crtime=now, mime=entry.attr.mime,
                    collection=self.collection,
                    replication=self.replication, md5=md5_hex)
        if self.filer.exists(dest):
            self.filer.delete_entry(dest)
        self.filer.create_entry(Entry(full_path=dest, attr=attr,
                                      chunks=chunks))

    def _chunk_fetcher(self):
        if self._fetch is None:
            from ..filer.stream import default_fetcher
            self._fetch = default_fetcher(self.master_url)
        return self._fetch

    def _prop_response(self, path: str, entry: Optional[Entry],
                       ok_props_only: bool = False) -> ET.Element:
        ns = "{%s}" % DAV_NS
        resp = ET.Element(ns + "response")
        href = urllib.parse.quote(path)
        if entry is not None and entry.is_directory and path != "/":
            href += "/"
        ET.SubElement(resp, ns + "href").text = href
        propstat = ET.SubElement(resp, ns + "propstat")
        prop = ET.SubElement(propstat, ns + "prop")
        if entry is not None:
            ET.SubElement(prop, ns + "displayname").text = \
                entry.name or "/"
            rt = ET.SubElement(prop, ns + "resourcetype")
            if entry.is_directory:
                ET.SubElement(rt, ns + "collection")
            else:
                ET.SubElement(prop, ns + "getcontentlength").text = \
                    str(entry.size())
                ET.SubElement(prop, ns + "getcontenttype").text = \
                    entry.attr.mime or "application/octet-stream"
                if entry.attr.md5:
                    ET.SubElement(prop, ns + "getetag").text = \
                        f'"{entry.attr.md5}"'
            ET.SubElement(prop, ns + "getlastmodified").text = \
                _rfc1123(entry.attr.mtime)
            ET.SubElement(prop, ns + "creationdate").text = \
                _iso8601(entry.attr.crtime)
        ET.SubElement(propstat, ns + "status").text = \
            "HTTP/1.1 200 OK"
        return resp

    def _multistatus(self, responses) -> Response:
        ns = "{%s}" % DAV_NS
        ET.register_namespace("D", DAV_NS)
        root = ET.Element(ns + "multistatus")
        for r in responses:
            root.append(r)
        body = b'<?xml version="1.0" encoding="utf-8"?>' + \
            ET.tostring(root)
        return Response(body, 207, 'application/xml; charset="utf-8"')
