"""ctypes wrapper for the native volume-server read plane.

The C++ library (`server/native/http_plane.cc`) serves plain needle GETs
on a second advertised port without the Python GIL in the loop — the
native analog of the reference's Go data plane (reference
weed/server/volume_server_handlers_read.go). The Python server stays the
source of truth: the plane answers only the fast path and 307-redirects
everything else (EC volumes, gzip-stored payloads, chunk manifests,
Seaweed-* pairs, resize queries) back to the owning Python server.

The index the plane serves from is a mirror, pushed from Python:
  - `register_volume(volume)` bulk-loads the needle map after a volume
    is loaded/created (and re-syncs after compaction commit);
  - `put`/`delete` mirror every write/delete as it happens (the .dat is
    flushed before the index update, so the plane's independent fd sees
    the bytes through the page cache).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
# SW_HTTP_PLANE_LIB overrides the library (e.g. an ASAN-instrumented
# build for the sanitizer test pass)
_LIB_PATH = os.environ.get(
    "SW_HTTP_PLANE_LIB",
    os.path.join(_LIB_DIR, "libseaweed_http.so"))

_lib = None
_lib_lock = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib or None
        if "SW_HTTP_PLANE_LIB" in os.environ and \
                not os.path.exists(_LIB_PATH):
            # an explicit override must never silently degrade into a
            # freshly compiled plain build (it usually points at an
            # instrumented variant)
            raise FileNotFoundError(
                f"SW_HTTP_PLANE_LIB={_LIB_PATH} does not exist")
        try:
            if not os.path.exists(_LIB_PATH):
                # compile only the library (build.sh also builds the
                # loadgen tool, which server startup must not wait for)
                import subprocess
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-fPIC", "-shared",
                     "-pthread", "-o", _LIB_PATH,
                     os.path.join(_LIB_DIR, "http_plane.cc")],
                    check=True, capture_output=True, timeout=60)
            lib = ctypes.CDLL(_LIB_PATH)
        except Exception:
            _lib = False
            return None
        lib.swhp_start.argtypes = [ctypes.c_char_p, ctypes.c_uint16,
                                   ctypes.c_char_p, ctypes.c_int]
        lib.swhp_start.restype = ctypes.c_void_p
        lib.swhp_port.argtypes = [ctypes.c_void_p]
        lib.swhp_port.restype = ctypes.c_uint16
        lib.swhp_add_volume.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                        ctypes.c_char_p, ctypes.c_int]
        lib.swhp_add_volume.restype = ctypes.c_int
        lib.swhp_remove_volume.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.swhp_remove_volume.restype = ctypes.c_int
        lib.swhp_put.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                 ctypes.c_uint64, ctypes.c_uint64,
                                 ctypes.c_uint32]
        lib.swhp_put.restype = ctypes.c_int
        lib.swhp_put_bulk.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                      ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_void_p, ctypes.c_int64]
        lib.swhp_put_bulk.restype = ctypes.c_int
        lib.swhp_delete.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                    ctypes.c_uint64]
        lib.swhp_delete.restype = ctypes.c_int
        lib.swhp_served.argtypes = [ctypes.c_void_p]
        lib.swhp_served.restype = ctypes.c_uint64
        lib.swhp_redirected.argtypes = [ctypes.c_void_p]
        lib.swhp_redirected.restype = ctypes.c_uint64
        lib.swhp_stop.argtypes = [ctypes.c_void_p]
        lib.swhp_stop.restype = None
        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


class NativeReadPlane:
    """One native fast-read server owned by a VolumeServer."""

    def __init__(self, host: str, port: int, fallback_hostport: str,
                 max_conns: int = 1024):
        lib = _load()
        if lib is None:
            raise RuntimeError("libseaweed_http.so unavailable")
        self._lib = lib
        self._h = lib.swhp_start(host.encode(), port,
                                 fallback_hostport.encode(), max_conns)
        if not self._h:
            raise RuntimeError(
                f"native read plane failed to listen on {host}:{port}")
        self.host = host
        self.port = lib.swhp_port(self._h)

    # -- volume lifecycle --------------------------------------------------
    def register_volume(self, volume) -> bool:
        """Open the .dat and bulk-load the volume's live needle map.

        The plane answers index misses with a redirect to the Python
        server, so the add-then-fill window is safe (windowed misses
        are served by the fallback, never 404'd). The needle map is
        snapshotted under the volume lock — it mutates under writes."""
        h = self._h
        if not h:
            return False
        rc = self._lib.swhp_add_volume(
            h, volume.id, volume.dat_path.encode(), volume.version)
        if rc != 0:
            return False
        import numpy as np
        with volume.lock:
            entries = list(volume.nm.items())
        keys, offsets, sizes = [], [], []
        for key, nv in entries:
            keys.append(key)
            offsets.append(nv.offset)
            sizes.append(nv.size)
        if keys:
            ka = np.asarray(keys, dtype=np.uint64)
            oa = np.asarray(offsets, dtype=np.uint64)
            sa = np.asarray(sizes, dtype=np.uint32)
            self._lib.swhp_put_bulk(
                self._h, volume.id,
                ka.ctypes.data_as(ctypes.c_void_p),
                oa.ctypes.data_as(ctypes.c_void_p),
                sa.ctypes.data_as(ctypes.c_void_p), len(keys))
        return True

    def unregister_volume(self, vid: int):
        h = self._h
        if h:
            self._lib.swhp_remove_volume(h, vid)

    # -- per-needle mirror -------------------------------------------------
    def put(self, vid: int, key: int, offset: int, size: int):
        h = self._h
        if h:
            self._lib.swhp_put(h, vid, key, offset, size)

    def delete(self, vid: int, key: int):
        h = self._h
        if h:
            self._lib.swhp_delete(h, vid, key)

    # -- stats / lifecycle -------------------------------------------------
    @property
    def served(self) -> int:
        # a scrape/status racing stop() must see 0, not hand the C side
        # a NULL handle
        h = self._h
        return int(self._lib.swhp_served(h)) if h else 0

    @property
    def redirected(self) -> int:
        h = self._h
        return int(self._lib.swhp_redirected(h)) if h else 0

    def stop(self):
        if self._h:
            self._lib.swhp_stop(self._h)
            self._h = None
