"""ctypes wrapper for the native volume-server read plane.

The C++ library (`server/native/http_plane.cc`) serves plain needle GETs
on a second advertised port without the Python GIL in the loop — the
native analog of the reference's Go data plane (reference
weed/server/volume_server_handlers_read.go). The Python server stays the
source of truth: the plane answers only the fast path and 307-redirects
everything else (EC volumes, gzip-stored payloads, chunk manifests,
Seaweed-* pairs, resize queries) back to the owning Python server.

The index the plane serves from is a mirror, pushed from Python:
  - `register_volume(volume)` bulk-loads the needle map after a volume
    is loaded/created (and re-syncs after compaction commit);
  - `put`/`delete` mirror every write/delete as it happens (the .dat is
    flushed before the index update, so the plane's independent fd sees
    the bytes through the page cache).
"""

from __future__ import annotations

import ctypes
import json
import os
import threading
from ..util import config
from ..util.locks import make_lock
from typing import Optional

_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
# SW_HTTP_PLANE_LIB overrides the library (e.g. an ASAN-instrumented
# build for the sanitizer test pass)
_LIB_PATH = config.env_str(
    "SW_HTTP_PLANE_LIB",
    os.path.join(_LIB_DIR, "libseaweed_http.so"))

_lib = None
_lib_lock = make_lock("native_plane._lib_lock")
# True once the one-time build (or load) failed and the server fell back
# to the Python path — mirrored into /metrics as the
# SeaweedFS_volumeServer_plane_build_failed gauge so a fleet silently
# running GIL-bound data planes is visible on a dashboard
BUILD_FAILED = False


def build_failed() -> bool:
    return BUILD_FAILED


def _compile():
    """One-shot g++ build of the library (build.sh also builds the
    loadgen tool, which server startup must not wait for). On failure
    the compiler's stderr is logged at warning level — a silent fall
    back to the Python path used to swallow it entirely."""
    import subprocess
    from ..util import glog
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-fPIC", "-shared",
             "-pthread", "-o", _LIB_PATH,
             os.path.join(_LIB_DIR, "http_plane.cc")],
            check=True, capture_output=True, timeout=60)
    except Exception as e:
        stderr = getattr(e, "stderr", b"") or b""
        glog.warningf(
            "native plane build failed (%s: %s) — falling back to the "
            "Python data plane; compiler stderr:\n%s",
            type(e).__name__, e,
            stderr.decode("utf-8", "replace").strip() or "(empty)")
        raise


def _load() -> Optional[ctypes.CDLL]:
    global _lib, BUILD_FAILED
    with _lib_lock:
        if _lib is not None:
            return _lib or None
        if config.env_is_set("SW_HTTP_PLANE_LIB") and \
                not os.path.exists(_LIB_PATH):
            # an explicit override must never silently degrade into a
            # freshly compiled plain build (it usually points at an
            # instrumented variant)
            raise FileNotFoundError(
                f"SW_HTTP_PLANE_LIB={_LIB_PATH} does not exist")
        try:
            src = os.path.join(_LIB_DIR, "http_plane.cc")
            if not os.path.exists(_LIB_PATH):
                _compile()
            elif not config.env_is_set("SW_HTTP_PLANE_LIB") and \
                    os.path.getmtime(_LIB_PATH) < os.path.getmtime(src):
                # stale build from before a source (possibly ABI)
                # change; rebuild before the first dlopen — replacing
                # the file after loading would keep serving the old
                # mapping for the process lifetime
                os.remove(_LIB_PATH)
                _compile()
            lib = ctypes.CDLL(_LIB_PATH)
        except Exception:
            BUILD_FAILED = True
            _lib = False
            return None
        lib.swhp_start.argtypes = [ctypes.c_char_p, ctypes.c_uint16,
                                   ctypes.c_char_p, ctypes.c_int]
        lib.swhp_start.restype = ctypes.c_void_p
        lib.swhp_port.argtypes = [ctypes.c_void_p]
        lib.swhp_port.restype = ctypes.c_uint16
        lib.swhp_add_volume.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                        ctypes.c_char_p, ctypes.c_int]
        lib.swhp_add_volume.restype = ctypes.c_int
        lib.swhp_remove_volume.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.swhp_remove_volume.restype = ctypes.c_int
        lib.swhp_put.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                 ctypes.c_uint64, ctypes.c_uint64,
                                 ctypes.c_uint32]
        lib.swhp_put.restype = ctypes.c_int
        lib.swhp_put_bulk.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                      ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_void_p, ctypes.c_int64]
        lib.swhp_put_bulk.restype = ctypes.c_int
        lib.swhp_delete.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                    ctypes.c_uint64]
        lib.swhp_delete.restype = ctypes.c_int
        lib.swhp_served.argtypes = [ctypes.c_void_p]
        lib.swhp_served.restype = ctypes.c_uint64
        lib.swhp_redirected.argtypes = [ctypes.c_void_p]
        lib.swhp_redirected.restype = ctypes.c_uint64
        lib.swhp_written.argtypes = [ctypes.c_void_p]
        lib.swhp_written.restype = ctypes.c_uint64
        lib.swhp_enable_writer.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int]
        lib.swhp_enable_writer.restype = ctypes.c_int
        lib.swhp_disable_writer.argtypes = [ctypes.c_void_p,
                                            ctypes.c_uint32]
        lib.swhp_disable_writer.restype = ctypes.c_int64
        lib.swhp_set_accept_posts.argtypes = [ctypes.c_void_p,
                                              ctypes.c_uint32,
                                              ctypes.c_int]
        lib.swhp_set_accept_posts.restype = ctypes.c_int
        lib.swhp_append.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                    ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_uint64, ctypes.c_uint32,
                                    ctypes.c_int, ctypes.c_uint32]
        lib.swhp_append.restype = ctypes.c_int64
        lib.swhp_lookup.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                    ctypes.c_uint64,
                                    ctypes.POINTER(ctypes.c_uint64),
                                    ctypes.POINTER(ctypes.c_uint32)]
        lib.swhp_lookup.restype = ctypes.c_int
        lib.swhp_writer_counters.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.swhp_writer_counters.restype = ctypes.c_int
        lib.swhp_stop.argtypes = [ctypes.c_void_p]
        lib.swhp_stop.restype = None
        # telemetry ABI — absent only in an explicitly overridden
        # pre-telemetry build (SW_HTTP_PLANE_LIB), where the wrapper
        # degrades to stats()=None instead of refusing to serve
        if hasattr(lib, "swhp_stats"):
            lib.swhp_stats_len.argtypes = []
            lib.swhp_stats_len.restype = ctypes.c_int
            lib.swhp_stats.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_uint64),
                                       ctypes.c_int]
            lib.swhp_stats.restype = ctypes.c_int
            lib.swhp_lat_bounds.argtypes = [
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
            lib.swhp_lat_bounds.restype = ctypes.c_int
            lib.swhp_slow_ring.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p, ctypes.c_int]
            lib.swhp_slow_ring.restype = ctypes.c_int
            lib.swhp_set_stats_enabled.argtypes = [ctypes.c_void_p,
                                                   ctypes.c_int]
            lib.swhp_set_stats_enabled.restype = None
            lib.swhp_set_slow_us.argtypes = [ctypes.c_void_p,
                                             ctypes.c_uint64]
            lib.swhp_set_slow_us.restype = None
        # group-commit durability ABI — absent in an explicitly
        # overridden pre-durability build (SW_HTTP_PLANE_LIB), where
        # appends keep the page-cache ack contract as before
        if hasattr(lib, "swhp_set_sync_mode"):
            lib.swhp_set_sync_mode.argtypes = [ctypes.c_void_p,
                                               ctypes.c_int,
                                               ctypes.c_uint64,
                                               ctypes.c_uint64]
            lib.swhp_set_sync_mode.restype = ctypes.c_int
            lib.swhp_sync_stats_len.argtypes = []
            lib.swhp_sync_stats_len.restype = ctypes.c_int
            lib.swhp_sync_stats.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_int]
            lib.swhp_sync_stats.restype = ctypes.c_int
        # EC + reconstructed-slab cache ABI — absent in an explicitly
        # overridden pre-cache build (SW_HTTP_PLANE_LIB); the wrapper
        # then keeps every EC read on the redirect path as before
        if hasattr(lib, "swhp_cache_put"):
            lib.swhp_ec_register.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64]
            lib.swhp_ec_register.restype = ctypes.c_int
            lib.swhp_ec_set_shard.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int,
                ctypes.c_char_p]
            lib.swhp_ec_set_shard.restype = ctypes.c_int
            lib.swhp_ec_put_bulk.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
            lib.swhp_ec_put_bulk.restype = ctypes.c_int
            lib.swhp_ec_delete.argtypes = [ctypes.c_void_p,
                                           ctypes.c_uint32,
                                           ctypes.c_uint64]
            lib.swhp_ec_delete.restype = ctypes.c_int
            lib.swhp_ec_unregister.argtypes = [ctypes.c_void_p,
                                               ctypes.c_uint32]
            lib.swhp_ec_unregister.restype = ctypes.c_int
            lib.swhp_cache_configure.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_uint64]
            lib.swhp_cache_configure.restype = None
            lib.swhp_cache_put.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int,
                ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64]
            lib.swhp_cache_put.restype = ctypes.c_int
            lib.swhp_cache_invalidate.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_uint32,
                                                  ctypes.c_int]
            lib.swhp_cache_invalidate.restype = ctypes.c_uint64
            lib.swhp_cache_stats_len.argtypes = []
            lib.swhp_cache_stats_len.restype = ctypes.c_int
            lib.swhp_cache_stats.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_int]
            lib.swhp_cache_stats.restype = ctypes.c_int
        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


def lat_bounds_us() -> tuple:
    """µs upper bounds of the plane's latency buckets (the +Inf bucket
    is implicit). Empty when the library is unavailable or predates the
    telemetry ABI."""
    lib = _load()
    if lib is None or not hasattr(lib, "swhp_lat_bounds"):
        return ()
    buf = (ctypes.c_uint64 * 32)()
    n = lib.swhp_lat_bounds(buf, 32)
    return tuple(int(buf[i]) for i in range(max(0, n)))


class NativeReadPlane:
    """One native fast-read server owned by a VolumeServer."""

    def __init__(self, host: str, port: int, fallback_hostport: str,
                 max_conns: int = 1024):
        lib = _load()
        if lib is None:
            raise RuntimeError("libseaweed_http.so unavailable")
        self._lib = lib
        self._h = lib.swhp_start(host.encode(), port,
                                 fallback_hostport.encode(), max_conns)
        if not self._h:
            raise RuntimeError(
                f"native read plane failed to listen on {host}:{port}")
        self.host = host
        self.port = lib.swhp_port(self._h)
        self._has_stats = hasattr(lib, "swhp_stats")
        if self._has_stats:
            # SW_PLANE_STATS=0 is the escape hatch that takes even the
            # relaxed-atomic bumps off the request path (the bench's
            # overhead assertion compares against this build)
            lib.swhp_set_stats_enabled(
                self._h, 1 if config.env_bool("SW_PLANE_STATS") else 0)
            lib.swhp_set_slow_us(
                self._h, max(0, config.env_int("SW_PLANE_SLOW_US")))
        self._has_cache = hasattr(lib, "swhp_cache_put")
        if self._has_cache:
            lib.swhp_cache_configure(
                self._h, max(0, config.env_int("SW_PLANE_CACHE_BYTES")))
        self._has_sync = hasattr(lib, "swhp_set_sync_mode")
        if self._has_sync:
            self.set_sync_mode(
                config.env_str("SW_PLANE_FSYNC_MODE"),
                config.env_int("SW_PLANE_FSYNC_BATCH_US"),
                config.env_int("SW_PLANE_FSYNC_MAX_PENDING"))

    # -- volume lifecycle --------------------------------------------------
    def register_volume(self, volume) -> bool:
        """Open the .dat and bulk-load the volume's live needle map.

        The plane answers index misses with a redirect to the Python
        server, so the add-then-fill window is safe (windowed misses
        are served by the fallback, never 404'd). The needle map is
        snapshotted under the volume lock — it mutates under writes."""
        h = self._h
        if not h:
            return False
        rc = self._lib.swhp_add_volume(
            h, volume.id, volume.dat_path.encode(), volume.version)
        if rc != 0:
            return False
        from ..storage.compact_map import snapshot_live_items
        with volume.lock:
            entries = snapshot_live_items(volume.nm)
        with entries:
            return self._bulk_load(volume, entries)

    def _bulk_load(self, volume, entries) -> bool:
        import numpy as np

        def put_chunk(keys, offsets, sizes):
            ka = np.asarray(keys, dtype=np.uint64)
            oa = np.asarray(offsets, dtype=np.uint64)
            sa = np.asarray(sizes, dtype=np.uint32)
            self._lib.swhp_put_bulk(
                self._h, volume.id,
                ka.ctypes.data_as(ctypes.c_void_p),
                oa.ctypes.data_as(ctypes.c_void_p),
                sa.ctypes.data_as(ctypes.c_void_p), len(keys))

        keys, offsets, sizes = [], [], []
        for key, nv in entries:
            keys.append(key)
            offsets.append(nv.offset)
            sizes.append(nv.size)
            if len(keys) >= (1 << 20):   # bound the staging lists
                put_chunk(keys, offsets, sizes)
                keys, offsets, sizes = [], [], []
        if keys:
            put_chunk(keys, offsets, sizes)
        return True

    def unregister_volume(self, vid: int):
        h = self._h
        if h:
            self._lib.swhp_remove_volume(h, vid)

    # -- per-needle mirror -------------------------------------------------
    def put(self, vid: int, key: int, offset: int, size: int):
        h = self._h
        if h:
            self._lib.swhp_put(h, vid, key, offset, size)

    def delete(self, vid: int, key: int):
        h = self._h
        if h:
            self._lib.swhp_delete(h, vid, key)

    # -- write lease -------------------------------------------------------
    def enable_writer(self, volume, file_size_limit: int = 0,
                      accept_posts: bool = False):
        """Hand the volume's write lease to the plane (caller holds
        volume.lock). The mirror must already be registered and exact —
        register_volume under the same lock hold. Returns a
        NativeWriter (volume.fast_writer), or None on failure."""
        h = self._h
        if not h:
            return None
        from ..storage.types import max_volume_size
        tail = volume.size()
        rc = self._lib.swhp_enable_writer(
            h, volume.id, volume.idx_path.encode(), volume.offset_width,
            tail, max_volume_size(volume.offset_width),
            int(file_size_limit), 1 if accept_posts else 0)
        if rc != 0:
            return None
        return NativeWriter(self, volume.id)

    def disable_writer(self, vid: int) -> int:
        """Take the lease back (mutex barrier in C++). Returns the
        final tail offset, or -1 when no writer was active."""
        h = self._h
        if not h:
            return -1
        return int(self._lib.swhp_disable_writer(h, vid))

    # -- EC volumes + reconstructed-slab cache -----------------------------
    def register_ec_volume(self, ev, slab_bytes: int) -> bool:
        """Push an EC volume's geometry, local shard files and .ecx
        index mirror into the plane. slab_bytes must match the Python
        engine's slab size — cached slabs are addressed by index.

        Safe to call repeatedly (every mount/unmount re-syncs): a fresh
        record replaces the old one, so the shard set and index can
        never go stale. Index misses redirect to Python, so the
        register-then-fill window is served, never 404'd."""
        h = self._h
        if not h or not self._has_cache:
            return False
        from ..ec.constants import (LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
                                    TOTAL_SHARDS)
        try:
            dat_size = ev._dat_size_hint()
        except Exception:
            return False
        rc = self._lib.swhp_ec_register(
            h, ev.vid, ev.version, dat_size, LARGE_BLOCK_SIZE,
            SMALL_BLOCK_SIZE, int(slab_bytes))
        if rc != 0:
            return False
        for sid in range(TOTAL_SHARDS):
            shard = ev.shards.get(sid)
            self._lib.swhp_ec_set_shard(
                h, ev.vid, sid,
                shard.path.encode() if shard is not None else None)
        return self._bulk_load_ecx(ev)

    def _bulk_load_ecx(self, ev) -> bool:
        """Snapshot the .ecx under its lock and push every entry —
        tombstones included, so a deleted needle redirects (Python
        404s) instead of being resurrected by a re-sync."""
        import numpy as np
        from ..storage.needle_map import bytes_to_entry
        from ..storage.types import entry_size
        rec_size = entry_size(ev.offset_width)
        with ev.ecx_lock:
            ev.ecx_file.seek(0)
            raw = ev.ecx_file.read(ev.ecx_size)
        keys, offsets, sizes = [], [], []

        def put_chunk():
            ka = np.asarray(keys, dtype=np.uint64)
            oa = np.asarray(offsets, dtype=np.uint64)
            sa = np.asarray(sizes, dtype=np.uint32)
            self._lib.swhp_ec_put_bulk(
                self._h, ev.vid,
                ka.ctypes.data_as(ctypes.c_void_p),
                oa.ctypes.data_as(ctypes.c_void_p),
                sa.ctypes.data_as(ctypes.c_void_p), len(keys))

        for pos in range(0, len(raw) - rec_size + 1, rec_size):
            key, offset, size = bytes_to_entry(raw[pos:pos + rec_size])
            keys.append(key)
            offsets.append(offset)
            sizes.append(size)
            if len(keys) >= (1 << 20):  # bound the staging lists
                put_chunk()
                keys, offsets, sizes = [], [], []
        if keys:
            put_chunk()
        return True

    def unregister_ec_volume(self, vid: int):
        h = self._h
        if h and self._has_cache:
            self._lib.swhp_ec_unregister(h, vid)

    def ec_delete(self, vid: int, key: int):
        """Mirror an EC needle delete (tombstone, matching .ecx)."""
        h = self._h
        if h and self._has_cache:
            self._lib.swhp_ec_delete(h, vid, key)

    def cache_put(self, vid: int, sid: int, idx: int, data: bytes) -> bool:
        """Publish one reconstructed slab into the plane cache."""
        h = self._h
        if not h or not self._has_cache:
            return False
        return self._lib.swhp_cache_put(
            h, vid, sid, idx, data, len(data)) == 0

    def cache_invalidate(self, vid: int, sid: int = -1) -> int:
        """Drop cached slabs of (vid, sid), or all of vid when sid < 0.
        Returns the number of entries removed."""
        h = self._h
        if not h or not self._has_cache:
            return 0
        return int(self._lib.swhp_cache_invalidate(h, vid, sid))

    # field order of swhp_cache_stats's flat export
    _CACHE_STATS_FIELDS = (
        "puts", "put_bytes", "hits", "misses", "evictions", "invalidated",
        "entries", "bytes", "max_bytes", "degraded_served",
        "degraded_redirected", "ec_local_served")

    def cache_stats(self) -> Optional[dict]:
        """Slab-cache counters + EC serving outcomes, or None when the
        plane is stopped or the loaded library predates the cache ABI."""
        h = self._h
        if not h or not self._has_cache:
            return None
        n = int(self._lib.swhp_cache_stats_len())
        buf = (ctypes.c_uint64 * n)()
        if self._lib.swhp_cache_stats(h, buf, n) != n:
            return None
        return dict(zip(self._CACHE_STATS_FIELDS,
                        (int(x) for x in buf)))

    # -- stats / lifecycle -------------------------------------------------
    @property
    def served(self) -> int:
        # a scrape/status racing stop() must see 0, not hand the C side
        # a NULL handle
        h = self._h
        return int(self._lib.swhp_served(h)) if h else 0

    @property
    def redirected(self) -> int:
        h = self._h
        return int(self._lib.swhp_redirected(h)) if h else 0

    @property
    def written(self) -> int:
        h = self._h
        return int(self._lib.swhp_written(h)) if h else 0

    # field order of swhp_stats's flat export, ahead of the buckets
    _STATS_HEAD = ("requests", "status_1xx", "status_2xx", "status_3xx",
                   "status_4xx", "status_5xx", "bytes_sent", "redirects",
                   "index_misses", "lat_count", "lat_sum_us")

    def stats(self) -> Optional[dict]:
        """Telemetry snapshot: the flat counters plus the µs latency
        histogram as non-cumulative ``(bound_us, count)`` pairs, the
        trailing pair carrying ``None`` for the +Inf bucket. None when
        the plane is stopped or the loaded library predates the
        telemetry ABI."""
        h = self._h
        if not h or not self._has_stats:
            return None
        n = int(self._lib.swhp_stats_len())
        buf = (ctypes.c_uint64 * n)()
        if self._lib.swhp_stats(h, buf, n) != n:
            return None
        vals = [int(x) for x in buf]
        out = dict(zip(self._STATS_HEAD, vals))
        counts = vals[len(self._STATS_HEAD):]
        bounds = list(lat_bounds_us())[:len(counts) - 1]
        out["buckets"] = list(zip(bounds + [None], counts))
        return out

    def slow_requests(self) -> list:
        """Newest-first decoded slow-request ring (method, target,
        status, bytes, micros, unix_ms per entry)."""
        h = self._h
        if not h or not self._has_stats:
            return []
        buf = ctypes.create_string_buffer(1 << 16)
        n = self._lib.swhp_slow_ring(h, buf, len(buf))
        if n <= 0:
            return []
        try:
            return json.loads(buf.raw[:n].decode("utf-8", "replace"))
        except ValueError:
            return []

    # SW_PLANE_FSYNC_MODE values -> swhp_set_sync_mode codes
    _SYNC_MODES = {"off": 0, "group": 1, "always": 2}
    _SYNC_MODE_NAMES = {v: k for k, v in _SYNC_MODES.items()}

    def set_sync_mode(self, mode, batch_us: int, max_pending: int) -> bool:
        """Configure group-commit durability for subsequently-enabled
        write leases (live leases keep the mode they were enabled with —
        the volume server cycles leases to apply a change). mode is
        'off' | 'group' | 'always' (an unknown string falls back to
        'off' rather than refusing to serve)."""
        h = self._h
        if not h or not self._has_sync:
            return False
        code = self._SYNC_MODES.get(str(mode).strip().lower(), 0)
        return self._lib.swhp_set_sync_mode(
            h, code, max(0, int(batch_us)), max(1, int(max_pending))) == 0

    # field order of swhp_sync_stats's flat export, ahead of the buckets
    _SYNC_STATS_HEAD = ("mode", "batch_us", "max_pending", "batches",
                        "riders", "failures", "pending", "fsync_us_sum")

    def sync_stats(self) -> Optional[dict]:
        """Durability telemetry snapshot: config + batch/rider/failure
        counters, pending-queue depth, and the fsync µs histogram as
        ``(bound_us, count)`` pairs (trailing None = +Inf). The mode
        comes back as its knob string. None when the plane is stopped
        or the loaded library predates the durability ABI."""
        h = self._h
        if not h or not self._has_sync:
            return None
        n = int(self._lib.swhp_sync_stats_len())
        buf = (ctypes.c_uint64 * n)()
        if self._lib.swhp_sync_stats(h, buf, n) != n:
            return None
        vals = [int(x) for x in buf]
        out = dict(zip(self._SYNC_STATS_HEAD, vals))
        out["mode"] = self._SYNC_MODE_NAMES.get(out["mode"], "off")
        counts = vals[len(self._SYNC_STATS_HEAD):]
        bounds = list(lat_bounds_us())[:len(counts) - 1]
        out["buckets"] = list(zip(bounds + [None], counts))
        return out

    def set_stats_enabled(self, on: bool):
        h = self._h
        if h and self._has_stats:
            self._lib.swhp_set_stats_enabled(h, 1 if on else 0)

    def set_slow_us(self, us: int):
        """Runtime override of the SW_PLANE_SLOW_US ring threshold."""
        h = self._h
        if h and self._has_stats:
            self._lib.swhp_set_slow_us(h, max(0, int(us)))

    def stop(self):
        if self._h:
            self._lib.swhp_stop(self._h)
            self._h = None


class NativeWriter:
    """The write-lease handle a Volume holds while the native plane owns
    its .dat/.idx tails (volume.fast_writer). Implements the delegate
    surface storage/volume.py calls in writer mode: append (the one
    tail writer), lookup (the authoritative index), and the counter
    deltas the volume folds into its frozen needle-map counters."""

    __slots__ = ("_plane", "vid")

    def __init__(self, plane: "NativeReadPlane", vid: int):
        self._plane = plane
        self.vid = vid

    def append(self, blob: bytes, key: int, size_field: int,
               cookie: int = 0, check_cookie: bool = True) -> int:
        """Append one record; returns its .dat offset. size_field is
        the needle header Size (0xFFFFFFFF for tombstones). The
        overwrite/delete cookie is re-verified against the stored
        needle UNDER the append mutex — the Python-side pre-check
        races with concurrent fast-path POSTs."""
        from ..storage.volume import VolumeError
        h = self._plane._h
        if not h:
            raise OSError("native plane stopped")
        off = self._plane._lib.swhp_append(
            h, self.vid, blob, len(blob), key, size_field,
            1 if check_cookie else 0, cookie)
        if off == -2:
            raise VolumeError(
                f"volume {self.vid}: write exceeds the offset-width "
                f"addressing ceiling")
        if off == -4:
            raise VolumeError(
                f"needle {key}: mismatching cookie on overwrite")
        if off == -5:
            # durability lost (fsync poison / lease torn down
            # mid-batch): never acked, so the caller's retry through
            # the Python path is a harmless duplicate
            raise OSError(
                f"volume {self.vid}: group-commit batch poisoned — "
                f"durability of the append is unknown")
        if off < 0:
            raise OSError(
                f"native append failed on volume {self.vid} ({off})")
        return off

    def lookup(self, key: int):
        """(offset, size) from the plane's exact mirror, or None."""
        h = self._plane._h
        if not h:
            return None
        off = ctypes.c_uint64()
        size = ctypes.c_uint32()
        if self._plane._lib.swhp_lookup(h, self.vid, key,
                                        ctypes.byref(off),
                                        ctypes.byref(size)):
            return off.value, size.value
        return None

    def counters(self):
        """(puts, put_bytes, deletes, deleted_bytes, max_key, tail)."""
        h = self._plane._h
        if not h:
            return (0, 0, 0, 0, 0, 0)
        buf = (ctypes.c_uint64 * 6)()
        if self._plane._lib.swhp_writer_counters(h, self.vid, buf) != 0:
            return (0, 0, 0, 0, 0, 0)
        return tuple(int(x) for x in buf)

    def set_accept_posts(self, on: bool):
        h = self._plane._h
        if h:
            self._plane._lib.swhp_set_accept_posts(
                h, self.vid, 1 if on else 0)

    def release(self) -> int:
        """Hand the lease back (C++ mutex barrier; the group-commit
        committer drains its final batch first). Volume._demote_fast
        _writer calls this when an append came back ambiguous; the
        owning server's _writer_release does the same via the plane."""
        return self._plane.disable_writer(self.vid)
