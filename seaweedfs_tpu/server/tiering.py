"""Hot→warm write-through tiering — the f4 lifecycle seam.

The reference architecture (f4: replicated-hot Haystack volumes age
into erasure-coded warm storage) has no drain window: a volume being
demoted KEEPS serving reads from its hot replicas the whole time. This
module is the master-side driver of that lifecycle:

  * a leader-gated scan (``SW_TIER_INTERVAL_S``) walks the heartbeat
    topology for sealed volumes — readonly, or past
    ``SW_TIER_FULL_FRAC`` of the size limit — that have gone
    unmodified for ``SW_TIER_AGE_S`` seconds;
  * each candidate is demoted through the shell's encode flow over the
    shared stripe transport (``ec/transport.py``): freeze replicas →
    streaming encode+spread paced at ``SW_TIER_RATE_MBPS`` → mount EC
    shards → delete the hot replicas. Until that final delete, every
    read hits the hot copy; after it, reads come off the EC stripe
    (degraded-read path included) — the flip is the replica delete,
    and there is never a moment with neither copy mounted;
  * per-volume demotion state is served at ``GET /cluster/tiering``.

New client writes are never blocked: the demoted volume was sealed, so
assigns already route to other writable volumes; a failed demotion
unwinds (shards deleted, replicas thawed) inside ``do_ec_encode``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..util import config, glog
from ..util.locks import make_lock

# lifecycle states surfaced at /cluster/tiering
CANDIDATE = "candidate"
DEMOTING = "demoting"
WARM = "warm"
FAILED = "failed"


class VolumeTierer:
    """Background demotion driver owned by a MasterServer. The loop
    only acts while its master is the raft leader (followers hold no
    topology); a failover restarts the scan from the new leader's
    heartbeat-built view, and the ``do_ec_encode`` unwind discipline
    makes a half-finished demotion safe to retry."""

    def __init__(self, master):
        self.master = master
        self.enabled = config.env_bool("SW_TIER_ENABLE")
        self.interval = config.env_float("SW_TIER_INTERVAL_S")
        self.age_s = config.env_float("SW_TIER_AGE_S")
        self.concurrency = max(1, config.env_int("SW_TIER_CONCURRENCY"))
        self.rate_mbps = config.env_float("SW_TIER_RATE_MBPS")
        self.full_frac = config.env_float("SW_TIER_FULL_FRAC")
        self._lock = make_lock("tiering.VolumeTierer._lock")
        # vid -> {"state", "collection", "hot_bytes", ...}; the whole
        # dict IS the /cluster/tiering payload
        self._volumes: Dict[int, dict] = {}
        self._inflight: set = set()
        self.scans = 0
        self.demotions_ok = 0
        self.demotions_failed = 0
        self._thread: Optional[threading.Thread] = None
        if self.enabled and self.interval > 0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="master-tierer")

    # -- wiring ------------------------------------------------------------
    def start(self):
        if self._thread is not None:
            self._thread.start()

    def _loop(self):
        while not self.master._stop.wait(self.interval):
            if not self.master.is_leader():
                continue
            try:
                self.run_pass()
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                glog.V(0).infof("tier scan failed: %s", e)

    # -- candidate scan ----------------------------------------------------
    def _sealed_volumes(self) -> Dict[int, dict]:
        """Non-EC volumes whose every replica is sealed (readonly or
        past the full fraction) and old enough: vid -> summary."""
        topo = self.master.topology
        now = time.time()
        out: Dict[int, dict] = {}
        with topo.lock:
            limit = topo.volume_size_limit
            ec_vids = set(topo.ec_shard_map)
            by_vid: Dict[int, list] = {}
            for node in topo.all_nodes():
                for vid, vi in node.volumes.items():
                    by_vid.setdefault(vid, []).append(vi)
        for vid, infos in by_vid.items():
            if vid in ec_vids:
                continue
            vi = infos[0]
            sealed = vi.read_only or (
                limit and vi.size >= self.full_frac * limit)
            if not sealed:
                continue
            if vi.modified_at and now - vi.modified_at < self.age_s:
                continue
            out[vid] = {"collection": vi.collection or "",
                        "hot_bytes": int(vi.size),
                        "replicas": len(infos)}
        return out

    def run_pass(self) -> Dict[int, str]:
        """One scan+demote pass; returns {vid: state} for what it
        touched. Called by the loop, and directly by tests/bench (the
        loop thread only exists when SW_TIER_ENABLE is on)."""
        self.scans += 1
        sealed = self._sealed_volumes()
        with self._lock:
            for vid, summary in sealed.items():
                st = self._volumes.get(vid)
                if st is None or st["state"] == FAILED:
                    # failed demotions re-enter as candidates: the
                    # unwind thawed the replicas, nothing is lost
                    self._volumes[vid] = dict(summary, state=CANDIDATE)
            todo = [vid for vid, st in sorted(self._volumes.items())
                    if st["state"] == CANDIDATE
                    and vid not in self._inflight]
            todo = todo[:max(0, self.concurrency - len(self._inflight))]
            for vid in todo:
                self._inflight.add(vid)
                self._volumes[vid]["state"] = DEMOTING
        if not todo:
            self._export_gauges()
            return {}
        threads = [threading.Thread(
            target=self._demote_one, args=(vid,), daemon=True,
            name=f"tier-demote-{vid}") for vid in todo]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self._export_gauges()
        with self._lock:
            return {vid: self._volumes[vid]["state"] for vid in todo}

    # -- one demotion ------------------------------------------------------
    def _demote_one(self, vid: int):
        """Hot→warm via the shell encode verb: freeze → streaming
        encode+spread (paced) → mount → delete hot replicas. Reads are
        served by the hot copy until that last step — the no-drain
        flip."""
        import sys

        from ..shell.command_ec import do_ec_encode
        from ..shell.command_env import CommandEnv
        from ..stats.metrics import (MASTER_TIER_BYTES,
                                     MASTER_TIER_DEMOTIONS,
                                     MASTER_TIER_MBPS_GAUGE,
                                     MASTER_TIER_SECONDS)
        with self._lock:
            st = self._volumes[vid]
            hot_bytes = st.get("hot_bytes", 0)
            st["started_at"] = time.time()
        env = CommandEnv(self.master.url, out=sys.stderr)
        env.admin_timeout = 900.0
        timings: Dict = {}
        t0 = time.perf_counter()
        try:
            do_ec_encode(env, vid, mode="stream", timings=timings,
                         rate_mbps=self.rate_mbps)
        except Exception as e:  # noqa: BLE001 - recorded, retried next scan
            glog.V(0).infof("tier demotion of volume %s failed: %s",
                            vid, e)
            with self._lock:
                st.update(state=FAILED, error=str(e)[:300],
                          finished_at=time.time())
                self._inflight.discard(vid)
                self.demotions_failed += 1
            MASTER_TIER_DEMOTIONS.inc("failed")
            return
        wall = time.perf_counter() - t0
        mbps = (hot_bytes / wall / 1e6) if wall > 0 else 0.0
        with self._lock:
            st.update(state=WARM, wall_s=round(wall, 3),
                      demote_mbps=round(mbps, 2),
                      overlap_frac=timings.get("overlap_frac", 0.0),
                      trace_id=timings.get("trace_id", ""),
                      finished_at=time.time())
            self._inflight.discard(vid)
            self.demotions_ok += 1
        MASTER_TIER_DEMOTIONS.inc("ok")
        MASTER_TIER_SECONDS.inc(amount=wall)
        if hot_bytes:
            MASTER_TIER_BYTES.inc(amount=hot_bytes)
        MASTER_TIER_MBPS_GAUGE.set(round(mbps, 2))
        glog.V(0).infof(
            "volume %s demoted hot→warm: %.1f MB in %.2fs (%.1f MB/s, "
            "rate cap %s)", vid, hot_bytes / 1e6, wall, mbps,
            self.rate_mbps or "off")

    # -- observability -----------------------------------------------------
    def _export_gauges(self):
        from ..stats.metrics import MASTER_TIER_VOLUMES_GAUGE
        counts = {CANDIDATE: 0, DEMOTING: 0, WARM: 0, FAILED: 0}
        with self._lock:
            for st in self._volumes.values():
                counts[st["state"]] = counts.get(st["state"], 0) + 1
        for state, n in counts.items():
            MASTER_TIER_VOLUMES_GAUGE.set(n, state)

    def snapshot(self) -> dict:
        """The /cluster/tiering payload."""
        with self._lock:
            volumes = {str(vid): dict(st)
                       for vid, st in self._volumes.items()}
        return {
            "enabled": self.enabled,
            "scans": self.scans,
            "demotions_ok": self.demotions_ok,
            "demotions_failed": self.demotions_failed,
            "knobs": {
                "interval_s": self.interval,
                "age_s": self.age_s,
                "concurrency": self.concurrency,
                "rate_mbps": self.rate_mbps,
                "full_frac": self.full_frac,
            },
            "volumes": volumes,
        }
