"""Minimal HTML status dashboards (reference weed/server/master_ui/ +
volume_server_ui/ templates). Plain stdlib string templating — these
pages are operator glances, not apps."""

from __future__ import annotations

import html
import time

_PAGE = """<!doctype html><html><head><title>{title}</title><style>
body{{font-family:sans-serif;margin:2em;color:#222}}
table{{border-collapse:collapse;margin:1em 0}}
td,th{{border:1px solid #ccc;padding:4px 10px;text-align:left}}
th{{background:#f4f4f4}} h1{{font-size:1.3em}} .muted{{color:#888}}
</style></head><body><h1>{title}</h1>{body}
<p class="muted">seaweedfs_tpu &middot; {now}</p></body></html>"""


class Raw(str):
    """Marks ONE cell as trusted, pre-escaped markup. Everything else is
    escaped — confining the XSS trust decision to the specific cell
    instead of a page-wide flag."""


def _table(headers, rows) -> str:
    head = "".join(f"<th>{html.escape(str(h))}</th>" for h in headers)

    def cell(c):
        return str(c) if isinstance(c, Raw) else html.escape(str(c))

    body = "".join(
        "<tr>" + "".join(f"<td>{cell(c)}</td>" for c in row)
        + "</tr>" for row in rows)
    return f"<table><tr>{head}</tr>{body}</table>"


def render_page(title: str, sections, footer_html: str = "") -> bytes:
    """``footer_html`` is trusted markup appended after the sections."""
    body = ""
    for heading, headers, rows in sections:
        body += f"<h2>{html.escape(heading)}</h2>"
        body += _table(headers, rows)
    body += footer_html
    return _PAGE.format(title=html.escape(title), body=body,
                        now=time.strftime("%Y-%m-%d %H:%M:%S")).encode()


def traces_section(n: int = 8):
    """(heading, headers, rows) for the newest traces in the in-process
    ring — each row is one trace: id, root span, span count, phase
    breakdown, and the longest span's duration."""
    from ..util import tracing
    rows = []
    for t in tracing.RING.recent(n):
        phases = {}
        for s in t["spans"]:
            name = s.get("name")
            if name in tracing.PHASES:
                phases[name] = phases.get(name, 0.0) \
                    + (s.get("duration_s") or 0.0)
        breakdown = " ".join(f"{p}={phases[p]*1000:.0f}ms"
                             for p in tracing.PHASES if p in phases) or "-"
        rows.append((t["trace_id"][:16], t.get("root") or "-",
                     t["span_count"], breakdown,
                     f"{t['max_span_s']*1000:.1f}ms"))
    return ("Recent traces (/admin/traces)",
            ["trace", "root span", "spans", "ec phases", "longest span"],
            rows)


def master_status_page(master) -> bytes:
    topo = master.topology
    nodes = []
    with topo.lock:
        for n in topo.all_nodes():
            nodes.append((n.url, n.rack.id if n.rack else "",
                          len(n.volumes), len(n.ec_shards),
                          n.max_volume_count,
                          f"{time.time() - n.last_seen:.0f}s ago"))
        vols = []
        for node in topo.all_nodes():
            for vid, vi in sorted(node.volumes.items()):
                vols.append((vid, vi.collection or "-", node.url,
                             f"{vi.size / 1e6:.1f} MB",
                             vi.file_count, vi.delete_count))
    sections = [
        ("Cluster", ["leader", "peers", "volume size limit"],
         [(master.leader_url() or master.url,
           ", ".join(master.raft.peers) if master.raft else "-",
           f"{topo.volume_size_limit >> 20} MB")]),
        ("Volume servers", ["url", "rack", "volumes", "ec shards",
                            "max", "last heartbeat"], nodes),
        ("Volumes", ["id", "collection", "server", "size", "files",
                     "deleted"], vols[:200]),
        traces_section(),
    ]
    return render_page(f"Master {master.url}", sections)


def volume_status_page(vs) -> bytes:
    vols, ecs = [], []
    for loc in vs.store.locations:
        with loc.lock:  # mounts/deletes mutate these dicts concurrently
            for vid, v in sorted(loc.volumes.items()):
                vols.append((vid, v.collection or "-", loc.directory,
                             f"{v.size() / 1e6:.1f} MB", v.file_count(),
                             v.deleted_count(),
                             "ro" if v.readonly else "rw",
                             v.index_kind, v.offset_width))
            for vid, ev in sorted(loc.ec_volumes.items()):
                ecs.append((vid, ev.collection or "-",
                            ",".join(map(str, ev.shard_ids()))))
    sections = [
        ("Server", ["url", "master", "data center", "rack"],
         [(vs.url, vs.master_url, vs.store.data_center or "-",
           vs.store.rack or "-")]),
        ("Volumes", ["id", "collection", "dir", "size", "files",
                     "deleted", "mode", "index", "offw"], vols),
        ("EC volumes", ["id", "collection", "shards"], ecs),
        traces_section(),
    ]
    return render_page(f"Volume server {vs.url}", sections)
