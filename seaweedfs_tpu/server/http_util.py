"""Tiny stdlib HTTP server framework + client helpers.

Single dependency-free layer used by every server: prefix/exact routing on
ThreadingHTTPServer, JSON responses, multipart/form-data parsing (the
reference's upload format), and urllib-based client calls.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
from ..util.locks import make_lock
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from ..util import config, tracing


class HttpError(Exception):
    def __init__(self, status: int, message: str = ""):
        super().__init__(message)
        self.status = status
        self.message = message


class Request:
    def __init__(self, handler: BaseHTTPRequestHandler):
        self.handler = handler
        parsed = urllib.parse.urlparse(handler.path)
        self.path = parsed.path
        self.raw_query = parsed.query
        self.query: Dict[str, str] = {
            k: v[0] for k, v in
            urllib.parse.parse_qs(parsed.query, keep_blank_values=True).items()}
        self.method = handler.command
        self.headers = handler.headers
        self._body: Optional[bytes] = None

    @property
    def body(self) -> bytes:
        if self._body is None:
            if self._chunked():
                self._body = self._read_chunked()
                return self._body
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                length = -1
            if length < 0:
                # malformed/negative: framing is unknowable — refuse
                # and sever rather than reading until EOF
                self.handler.close_connection = True
                self._body = b""
                raise HttpError(400, "bad Content-Length header")
            self._body = self.handler.rfile.read(length) if length else b""
        return self._body

    def _chunked(self) -> bool:
        return "chunked" in \
            (self.headers.get("Transfer-Encoding") or "").lower()

    def _read_chunked(self) -> bytes:
        """Decode a chunked transfer-encoded body (the framing
        post_chunked emits: streaming uploads whose size isn't known —
        or not yet complete — when the request line goes out). Any
        framing violation severs the connection: resynchronizing a
        keep-alive stream after a bad chunk header is not possible."""
        rfile = self.handler.rfile
        out: List[bytes] = []
        while True:
            line = rfile.readline(1 << 16)
            if not line or not line.endswith(b"\n"):
                self.handler.close_connection = True
                raise HttpError(400, "truncated chunked body")
            size_s = line.split(b";", 1)[0].strip()
            try:
                size = int(size_s, 16)
            except ValueError:
                self.handler.close_connection = True
                raise HttpError(400, "bad chunk size") from None
            if size == 0:
                # consume optional trailers up to the blank line
                while True:
                    t = rfile.readline(1 << 16)
                    if t in (b"\r\n", b"\n", b""):
                        break
                return b"".join(out)
            data = rfile.read(size)
            if len(data) != size:
                self.handler.close_connection = True
                raise HttpError(400, "truncated chunk")
            out.append(data)
            rfile.read(2)  # chunk-terminating CRLF

    def drain(self, cap: int = 4 << 20):
        """Discard any unread request body. Keep-alive framing depends
        on this: a handler that never touches .body would otherwise
        leave the payload in the socket, where it prepends itself to
        the next request line on the reused connection. Beyond ``cap``
        the connection is closed instead — reading a rejected
        volume-sized upload to completion would stall the thread for
        the whole transfer (Go's http.Server draws the same line)."""
        if self._body is not None:
            return
        if self._chunked():
            # unread chunked body: total size is unknowable up front, so
            # sever instead of decoding a possibly volume-sized stream
            self.handler.close_connection = True
            self._body = b""
            return
        try:
            left = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            # malformed header: framing is unknowable — sever instead
            # of masking the handler's response with a late error
            self.handler.close_connection = True
            self._body = b""
            return
        if left > cap:
            self.handler.close_connection = True
            self._body = b""
            return
        while left > 0:
            chunk = self.handler.rfile.read(min(left, 1 << 20))
            if not chunk:
                break
            left -= len(chunk)
        self._body = b""

    def json(self) -> dict:
        if not self.body:
            return {}
        return json.loads(self.body)

    def multipart_file(self) -> Optional[Tuple[str, str, bytes]]:
        """Parse the first file part of a multipart/form-data body.
        Returns (filename, content_type, data) or None."""
        ctype = self.headers.get("Content-Type", "")
        if not ctype.startswith("multipart/form-data"):
            return None
        m = re.search(r'boundary="?([^";]+)"?', ctype)
        if not m:
            return None
        boundary = m.group(1).encode()
        parts = self.body.split(b"--" + boundary)
        for part in parts:
            # each inner part is b"\r\n<headers>\r\n\r\n<data>\r\n";
            # strip exactly one CRLF per side — data may itself begin or
            # end with newline bytes that must survive
            if part.startswith(b"\r\n"):
                part = part[2:]
            if part.endswith(b"\r\n"):
                part = part[:-2]
            if not part or part in (b"--", b"--\r\n"):
                continue
            if b"\r\n\r\n" not in part:
                continue
            head, data = part.split(b"\r\n\r\n", 1)
            head_s = head.decode("utf-8", "replace")
            fn = re.search(r'filename="((?:[^"\\]|\\.)*)"', head_s)
            ct = re.search(r"Content-Type:\s*([^\r\n]+)", head_s, re.I)
            if fn is not None:
                name = fn.group(1).replace('\\"', '"') \
                    .replace("\\\\", "\\")
                return (name, ct.group(1).strip() if ct else "",
                        data)
        return None

    def upload_payload(self) -> Tuple[str, str, bytes]:
        """File data from multipart or raw body (reference accepts both)."""
        mp = self.multipart_file()
        if mp is not None:
            return mp
        return ("", self.headers.get("Content-Type", ""), self.body)


Route = Tuple[str, str, bool, Callable]


def traces_handler(req: Request) -> dict:
    """JSON view of the in-process trace ring, shared by every server
    role: ``/admin/traces?n=20`` for the newest traces, or
    ``/admin/traces?trace=<id>`` for one trace's spans."""
    tid = req.query.get("trace")
    if tid:
        return {"trace_id": tid, "spans": tracing.RING.get(tid)}
    n = int(req.query.get("n", "20"))
    return {"traces": tracing.RING.recent(n)}


def traces_export_handler(req: Request) -> dict:
    """Chrome trace-event JSON for one trace from this node's ring
    (``/admin/traces/export?trace=<id>``) — loadable in Perfetto as-is,
    and carrying enough in event args for shell ``trace.export`` to
    merge several nodes' exports into one skew-normalized timeline."""
    from ..util import trace_export
    tid = req.query.get("trace")
    if not tid:
        raise HttpError(400, "trace query parameter required")
    return trace_export.chrome_trace_events(tracing.RING.get(tid))


# one profile at a time per process — concurrent samplers would double
# the GIL-held stack-walk overhead and interleave their sample counts
_PROFILE_LOCK = make_lock("http_util._profile_lock")


def profile_handler(req: Request) -> "Response":
    """On-demand all-thread sampling profile, shared by every server
    role: ``POST /admin/profile?seconds=N`` samples for N seconds
    (clamped to SW_PROFILE_MAX_S) and returns collapsed stacks as
    text/plain — the folded format flamegraph.pl and speedscope ingest.
    A second request while one is running gets 409 instead of stacking
    sampler threads."""
    from ..util.profiling import SamplingProfiler
    try:
        seconds = float(req.query.get("seconds", "2"))
    except ValueError:
        raise HttpError(400, "seconds must be a number")
    if seconds <= 0:
        raise HttpError(400, "seconds must be > 0")
    seconds = min(seconds, config.env_float("SW_PROFILE_MAX_S"))
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise HttpError(409, "a profile is already running")
    try:
        folded = SamplingProfiler.run_for(seconds)
    finally:
        _PROFILE_LOCK.release()
    return Response(folded.encode("utf-8"), 200,
                    "text/plain; charset=utf-8")


def process_memory_stats() -> dict:
    """Peak RSS of this process (reference statsMemoryHandler).
    ru_maxrss is kilobytes on Linux but BYTES on macOS/BSD."""
    import resource
    import sys
    ru = resource.getrusage(resource.RUSAGE_SELF)
    kb = ru.ru_maxrss // 1024 if sys.platform == "darwin" \
        else ru.ru_maxrss
    return {"maxrss_kb": kb}


class Router:
    def __init__(self):
        self.routes: List[Route] = []
        self.fallback: Optional[Callable] = None
        # runs before every handler (guard checks); may raise HttpError
        self.before: Optional[Callable] = None
        # observe(op_label, seconds, ok) after every request — the
        # servers plug their metric registries in here
        self.observe: Optional[Callable] = None
        # "host:port" of the owning server, set once its port is known;
        # stamped onto every server span so a merged trace export can
        # attribute spans to nodes even when in-process servers share
        # one trace ring
        self.node: Optional[str] = None

    def add(self, method: str, path: str, fn: Callable,
            prefix: bool = False):
        self.routes.append((method, path, prefix, fn))

    def set_fallback(self, fn: Callable):
        self.fallback = fn

    def dispatch(self, req: Request):
        import time as _time
        # continue a remote trace if the caller sent a traceparent; the
        # span becomes the handler thread's current span, so spans made
        # inside the handler (EC phases, peer fetches) link to it
        srv_span = tracing.start_span(
            f"{req.method} {req.path.split('?')[0]}",
            traceparent=req.headers.get(tracing.TRACEPARENT_HEADER))
        if self.node:
            srv_span.tags.setdefault("node", self.node)
        t0 = _time.monotonic()
        label = None
        try:
            label, fn = self._route(req)
            srv_span.name = label
            out = fn(req)
            if self.observe is not None:
                self.observe(label, _time.monotonic() - t0, True)
            return out
        except Exception as e:
            srv_span.tags.setdefault("error", type(e).__name__)
            if self.observe is not None:
                # label stays low-cardinality: the raw path would mint a
                # new Prometheus series per fid/404 probe
                self.observe(label or f"{req.method} unrouted",
                             _time.monotonic() - t0, False)
            raise
        finally:
            tracing.finish_span(srv_span)

    def _dispatch(self, req: Request):
        label, fn = self._route(req)
        return fn(req)

    def _route(self, req: Request):
        """(metric label, handler) for a request; raises 404."""
        if self.before is not None:
            self.before(req)
        for method, path, prefix, fn in self.routes:
            if method != "*" and method != req.method:
                continue
            if (prefix and req.path.startswith(path)) or req.path == path:
                return f"{method} {path}", fn
        if self.fallback is not None:
            return f"{req.method} data", self.fallback
        raise HttpError(404, f"no route for {req.method} {req.path}")


def _make_handler(router: Router):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # response headers and small bodies go out in separate writes;
        # without NODELAY, Nagle holds the second write hostage to the
        # peer's delayed ACK (millisecond-scale stalls per request)
        disable_nagle_algorithm = True
        # reap idle keep-alive connections: each one pins a handler
        # thread, and pooled clients keep up to 32 per peer open.
        # Applies to socket reads only — a long-poll that WAITS before
        # responding is unaffected; only >75s gaps mid-read close
        timeout = 75

        def log_message(self, fmt, *args):  # quiet
            pass

        def _run(self):
            req = Request(self)
            try:
                try:
                    result = router.dispatch(req)
                finally:
                    req.drain()
            except HttpError as e:
                self._send_json({"error": e.message or str(e)}, e.status)
                return
            except BrokenPipeError:
                return
            except Exception as e:  # noqa: BLE001
                self._send_json({"error": f"{type(e).__name__}: {e}"}, 500)
                return
            if result is None:
                self._send_json({}, 200)
            elif isinstance(result, Response):
                result.send(self)
            else:
                self._send_json(result, 200)

        def _send_json(self, obj, status: int):
            data = json.dumps(obj).encode()
            try:
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            except (BrokenPipeError, ConnectionResetError):
                pass

        do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _run
        # WebDAV verbs (reference weed/server/webdav_server.go uses
        # golang.org/x/net/webdav which handles the same set)
        do_OPTIONS = do_PROPFIND = do_PROPPATCH = do_MKCOL = _run
        do_MOVE = do_COPY = do_LOCK = do_UNLOCK = _run

    return Handler


class Response:
    """Non-JSON response (bytes, custom status/headers).

    content_length overrides the advertised Content-Length — a HEAD
    response must advertise the size a GET would return while sending no
    body (HTTP/1.1 semantics; boto3 and rclone size objects this way)."""

    def __init__(self, body: bytes = b"", status: int = 200,
                 content_type: str = "application/octet-stream",
                 headers: Optional[dict] = None,
                 content_length: Optional[int] = None,
                 body_path: Optional[str] = None,
                 body_range: Optional[tuple] = None):
        self.body = body
        self.status = status
        self.content_type = content_type
        self.headers = headers or {}
        self.content_length = content_length
        # streaming variant: serve (offset, size) of a file without
        # buffering it — bulk pulls (.dat tier/backup) are volume-sized
        self.body_path = body_path
        self.body_range = body_range

    def send(self, handler: BaseHTTPRequestHandler):
        src = None
        if self.body_path is not None:
            # open + stat BEFORE any header goes out: a vanished or
            # shrunken file (compaction / tier-upload race) must become
            # a clean error response, and the advertised Content-Length
            # must be bytes the stream can actually deliver
            try:
                src = open(self.body_path, "rb")
                file_size = os.fstat(src.fileno()).st_size
            except OSError as e:
                if src is not None:
                    src.close()
                handler.send_error(404, str(e))
                return
            off, size = self.body_range or (0, file_size)
            off = min(off, file_size)
            size = min(size, file_size - off)
            length = size
        else:
            length = self.content_length if self.content_length is not None \
                else len(self.body)
        try:
            handler.send_response(self.status)
            handler.send_header("Content-Type", self.content_type)
            handler.send_header("Content-Length", str(length))
            for k, v in self.headers.items():
                handler.send_header(k, v)
            handler.end_headers()
            if handler.command == "HEAD":
                return
            if src is not None:
                src.seek(off)
                left = size
                while left > 0:
                    chunk = src.read(min(1 << 20, left))
                    if not chunk:
                        break
                    handler.wfile.write(chunk)
                    left -= len(chunk)
            else:
                handler.wfile.write(self.body)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            if src is not None:
                src.close()


# -- transport security ------------------------------------------------------
# Reference weed/security/tls.go: optional TLS on every surface. One
# process-wide configuration (cert/key for servers, CA for clients) so
# the hundreds of "http://{host}" call sites need no changes: when TLS
# is on, http_call/http_download upgrade the scheme, and every
# HttpServer wraps its socket. Single-scheme by design, like the
# reference's all-or-nothing grpc TLS config.
_TLS = {"cert": "", "key": "", "ca": "", "client_ctx": None,
        "server_ctx": None, "mutual": False}


def configure_tls(cert_file: str = "", key_file: str = "",
                  ca_file: str = "", mutual: bool = False):
    """Enable TLS: servers present cert/key; clients verify against ca
    (or the cert itself for self-signed deployments). A cert without a
    key (or vice versa) is refused outright — the half-configured
    alternative serves plaintext while rewriting outbound URLs to
    https, which only surfaces as baffling handshake errors later.

    ``mutual=True`` is the reference's cluster-plane posture
    (weed/security/tls.go:34-40 ``ClientAuth:
    RequireAndVerifyClientCert``): servers ask every connection for a
    CA-verified client certificate, and the cluster-internal routes
    (heartbeat, admin, raft, watch — require_client_cert call sites)
    reject connections that presented none. Public data routes
    (reads, S3, filer) stay server-TLS on the same listener, which is
    why the socket uses CERT_OPTIONAL + per-route enforcement rather
    than failing every certless handshake outright. Outbound cluster
    calls present cert/key as their client identity
    (tls.go:55-66)."""
    import ssl
    clear_conn_pool()  # drop conns from the previous config
    if bool(cert_file) != bool(key_file):
        raise ValueError("TLS needs BOTH cert and key (got only one); "
                         "pass just ca for a client-only configuration")
    if mutual and not ca_file:
        raise ValueError("mutual TLS needs a CA to verify client "
                         "certificates against")
    _TLS["cert"], _TLS["key"], _TLS["ca"] = cert_file, key_file, ca_file
    _TLS["mutual"] = bool(mutual)
    if cert_file and key_file:
        sctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        sctx.load_cert_chain(cert_file, key_file)
        if mutual:
            # OPTIONAL at the handshake, REQUIRED per-route: a client
            # cert that fails CA verification still aborts the
            # handshake; absence is tolerated here and rejected by
            # require_client_cert on internal routes
            sctx.verify_mode = ssl.CERT_OPTIONAL
            sctx.load_verify_locations(ca_file)
        _TLS["server_ctx"] = sctx
    cctx = ssl.create_default_context(cafile=ca_file or cert_file or None)
    cctx.check_hostname = False  # cluster peers are addressed by ip:port
    if cert_file and key_file:
        # cluster peers authenticate outbound calls with the same
        # keypair they serve with (reference tls.go LoadClientTLS)
        cctx.load_cert_chain(cert_file, key_file)
    _TLS["client_ctx"] = cctx


def reset_tls():
    _TLS.update({"cert": "", "key": "", "ca": "", "client_ctx": None,
                 "server_ctx": None, "mutual": False})
    clear_conn_pool()  # pooled conns carry the previous TLS context


def tls_enabled() -> bool:
    return _TLS["server_ctx"] is not None


def mtls_enabled() -> bool:
    return tls_enabled() and _TLS["mutual"]


def require_client_cert(req: "Request"):
    """Reject a cluster-internal request whose connection presented no
    CA-verified client certificate (no-op unless mutual TLS is on).
    The handshake already aborted any UNverifiable cert, so a
    non-empty peer cert here means CA-verified."""
    if not mtls_enabled():
        return
    conn = req.handler.connection
    cert = conn.getpeercert() if hasattr(conn, "getpeercert") else None
    if not cert:
        raise HttpError(
            403, "client certificate required on cluster-internal "
                 "routes")


def _client_url(url: str) -> str:
    if _TLS["client_ctx"] is not None and url.startswith("http://"):
        return "https://" + url[len("http://"):]
    return url


class _TunedHTTPServer(ThreadingHTTPServer):
    # the stdlib default backlog of 5 drops SYNs under concurrent
    # clients (each drop costs a ~200ms+ retransmit — visible as p99
    # latency spikes); the reference's Go listener uses the OS default
    # (somaxconn)
    request_queue_size = 128
    daemon_threads = True

    def __init__(self, *args, **kwargs):
        self._client_socks: set = set()
        self._conn_lock = make_lock("http_util._conn_lock")
        super().__init__(*args, **kwargs)

    # track live client sockets so stop() can sever keep-alive
    # connections — shutdown() only stops the accept loop, and pooled
    # clients would otherwise keep talking to a "stopped" server
    def get_request(self):
        sock, addr = super().get_request()
        with self._conn_lock:
            self._client_socks.add(sock)
        return sock, addr

    def shutdown_request(self, request):
        with self._conn_lock:
            self._client_socks.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self):
        # shutdown ONLY — never close() a socket another thread may be
        # mid-write on: close frees the fd number, a concurrently
        # opened socket (e.g. this process's own client pool) can
        # reuse it, and the handler's buffered response bytes would
        # land inside an unrelated connection. shutdown wakes the
        # owning handler thread (EOF/EPIPE), which closes the fd
        # exactly once via shutdown_request.
        with self._conn_lock:
            socks = list(self._client_socks)
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class HttpServer:
    def __init__(self, port: int, router: Router, host: str = "127.0.0.1"):
        self.router = router
        self.httpd = _TunedHTTPServer((host, port), _make_handler(router))
        if _TLS["server_ctx"] is not None:
            self.httpd.socket = _TLS["server_ctx"].wrap_socket(
                self.httpd.socket, server_side=True)
        self.port = self.httpd.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name=f"http-serve-{self.port}")
        self._thread.start()
        return self

    def _serve(self):
        # shutdown() latency is bounded by the accept-loop poll; the
        # tier-1 conftest drops SW_HTTP_POLL_S to ~20 ms so hundreds of
        # per-test server stops don't each eat the stdlib's 0.5 s
        self.httpd.serve_forever(
            poll_interval=max(0.001, config.env_float("SW_HTTP_POLL_S")))

    def stop(self):
        # shutdown() blocks on serve_forever()'s ack; if start() never ran
        # there is no loop to ack and the call would deadlock.
        if self._thread is not None:
            self.httpd.shutdown()
        self.httpd.server_close()
        self.httpd.close_all_connections()


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def parse_range(rng: str, size: int) -> Optional[Tuple[int, int]]:
    """Parse a `bytes=a-b` Range header against a `size`-byte resource.

    Returns (offset, length), or None when the header is absent or not
    a bytes range. Raises HttpError(416) for malformed or unsatisfiable
    ranges. Only the first range of a multi-range spec is honored."""
    if not rng or not rng.startswith("bytes="):
        return None
    spec = rng[6:].split(",")[0]
    s, _, e = spec.partition("-")
    try:
        if s == "":
            offset = max(size - int(e), 0)
            length = size - offset
        else:
            offset = int(s)
            end = min(int(e), size - 1) if e else size - 1
            length = end - offset + 1
    except ValueError:
        raise HttpError(416, f"bad range {rng}") from None
    if length < 0 or (offset >= size and size > 0):
        raise HttpError(416, f"unsatisfiable range {rng}")
    return offset, length


# -- client helpers ---------------------------------------------------------
#
# Cluster-internal calls ride a keep-alive connection pool: urllib opens
# (and tears down) a fresh TCP connection per request, which caps a
# chatty data plane at connection-churn rate (SYN/FIN per needle write,
# TIME_WAIT pileups, Nagle stalls on the two-write request pattern).
# The reference's Go http.Client pools by default; this is the same
# discipline. External endpoints (webhooks, SQS, cloud sinks) keep the
# urllib path — low-rate, and their TLS contexts differ.

import http.client as _httpc

# pool entries are (conn, parked_at) — the park time drives idle-age
# eviction: a peer's keep-alive timeout (or an LB's) closes connections
# we would otherwise only discover stale at reuse, and long-lived shells
# would pin sockets to servers they talked to once
_POOL: Dict[Tuple[str, str], List] = {}
_POOL_LOCK = make_lock("http_util._POOL_LOCK")
_POOL_MAX_PER_HOST = 32
_POOL_MAX_IDLE_ENV = "SW_HTTP_POOL_MAX_IDLE_S"
# churn counters, mirrored into /metrics (http_pool_churn_total{event=})
POOL_STATS = {"created": 0, "reused": 0, "evicted_stale": 0,
              "evicted_idle": 0, "evicted_overflow": 0}
_RETRIABLE_STALE = (_httpc.RemoteDisconnected, _httpc.BadStatusLine,
                    ConnectionResetError, BrokenPipeError)


def _pool_max_idle_s() -> float:
    return config.env_float(_POOL_MAX_IDLE_ENV)


def _pool_count(event: str, n: int = 1):
    with _POOL_LOCK:
        POOL_STATS[event] += n


def pool_stats_snapshot() -> Dict[str, int]:
    with _POOL_LOCK:
        return dict(POOL_STATS)


def _new_conn(scheme: str, netloc: str, timeout: float):
    if scheme == "https":
        return _httpc.HTTPSConnection(netloc, timeout=timeout,
                                      context=_TLS["client_ctx"])
    return _httpc.HTTPConnection(netloc, timeout=timeout)


def _sock_is_stale(sock) -> bool:
    """A pooled idle socket that polls readable has either a FIN (peer
    closed the idle connection — the common post-restart case) or
    unexpected bytes; both mean: don't reuse. One zero-timeout select."""
    import select
    try:
        r, _, _ = select.select([sock], [], [], 0)
        return bool(r)
    except (OSError, ValueError):
        return True


def _pool_get(scheme: str, netloc: str, timeout: float):
    """-> (conn, reused). New connections get TCP_NODELAY on connect.
    Pops newest-first (LIFO keeps hot sockets hot) and evicts entries
    past the idle-age cap or failing the readable-peek stale check."""
    max_idle = _pool_max_idle_s()
    while True:
        with _POOL_LOCK:
            stack = _POOL.get((scheme, netloc))
            entry = stack.pop() if stack else None
        if entry is None:
            _pool_count("created")
            return _new_conn(scheme, netloc, timeout), False
        conn, parked_at = entry
        if max_idle > 0 and time.monotonic() - parked_at > max_idle:
            conn.close()
            _pool_count("evicted_idle")
            continue
        if conn.sock is not None and _sock_is_stale(conn.sock):
            conn.close()
            _pool_count("evicted_stale")
            continue
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        _pool_count("reused")
        return conn, True


def _pool_put(scheme: str, netloc: str, conn):
    """Park a connection. Also sweeps aged-out entries from the bottom
    of the stack — LIFO reuse means the oldest entries are never popped
    under steady load, so without the sweep they'd pin sockets
    forever."""
    now = time.monotonic()
    max_idle = _pool_max_idle_s()
    aged = []
    overflow = None
    with _POOL_LOCK:
        stack = _POOL.setdefault((scheme, netloc), [])
        if max_idle > 0:
            while stack and now - stack[0][1] > max_idle:
                aged.append(stack.pop(0)[0])
        if len(stack) < _POOL_MAX_PER_HOST:
            stack.append((conn, now))
        else:
            overflow = conn
        POOL_STATS["evicted_idle"] += len(aged)
        if overflow is not None:
            POOL_STATS["evicted_overflow"] += 1
    for c in aged:
        c.close()
    if overflow is not None:
        overflow.close()


def clear_conn_pool():
    """Drop every pooled connection (tests; TLS reconfiguration)."""
    with _POOL_LOCK:
        for stack in _POOL.values():
            for conn, _ in stack:
                conn.close()
        _POOL.clear()


def _nodelay(conn):
    if conn.sock is not None:
        try:
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                 1)
        except OSError:
            pass


def _traced_headers(headers: Optional[dict]) -> dict:
    """Inject the W3C ``traceparent`` on cluster-internal calls so the
    receiving server's span continues this caller's trace (no-op when
    the caller already set one, e.g. a redirect re-entry)."""
    h = dict(headers) if headers else {}
    if tracing.TRACEPARENT_HEADER not in h:
        h[tracing.TRACEPARENT_HEADER] = tracing.outbound_traceparent()
    return h


def _pooled_call(method: str, url: str, body, headers: dict,
                 timeout: float, max_redirects: int = 5,
                 want_headers: bool = False,
                 encode_chunked: bool = False):
    headers = _traced_headers(headers)
    parsed = urllib.parse.urlsplit(url)
    netloc, scheme = parsed.netloc, parsed.scheme
    target = parsed.path or "/"
    if parsed.query:
        target += "?" + parsed.query
    # A stale keep-alive connection fails at send/first-byte; retry once
    # on a fresh connection — but only for idempotent methods with a
    # replayable body. A POST whose server died between processing and
    # responding must NOT silently re-execute (double assign/publish) —
    # Go's http.Client draws the same line. Streaming bodies cannot be
    # re-sent at all, so they always go out on a FRESH connection
    # (their transfer time dwarfs the handshake).
    replayable = not encode_chunked and \
        (body is None or isinstance(body, (bytes, bytearray)))
    idempotent = method in ("GET", "HEAD", "DELETE", "PUT")
    attempts = 2 if (replayable and idempotent) else 1
    for attempt in range(attempts):
        if replayable:
            conn, reused = _pool_get(scheme, netloc, timeout)
        else:
            conn, reused = _new_conn(scheme, netloc, timeout), False
        try:
            if conn.sock is None:
                conn.connect()
                _nodelay(conn)
            conn.request(method, target, body=body, headers=headers,
                         encode_chunked=encode_chunked)
            resp = conn.getresponse()
            data = resp.read()
        except _RETRIABLE_STALE:
            conn.close()
            if reused and attempt + 1 < attempts:
                continue
            raise
        except Exception:
            conn.close()
            raise
        if resp.will_close:
            conn.close()
        else:
            _pool_put(scheme, netloc, conn)
        # 307/308 preserve method+body by definition — the native write
        # plane answers off-fast-path POSTs this way (redirect to the
        # owning Python server); other 3xx follow only for GET/HEAD
        follow = method in ("GET", "HEAD") or \
            (resp.status in (307, 308) and replayable)
        if 300 <= resp.status < 400 and resp.getheader("Location") \
                and follow and max_redirects > 0:
            loc = urllib.parse.urljoin(url, resp.getheader("Location"))
            # redirect targets are emitted as plain http (volume read
            # redirects) — re-apply the cluster TLS scheme rewrite
            return _pooled_call(method, _client_url(loc), body, headers,
                                timeout, max_redirects - 1,
                                want_headers)
        if resp.status >= 400:
            detail = data.decode("utf-8", "replace")[:500]
            raise HttpError(resp.status, f"{method} {url}: {detail}")
        if want_headers:
            return data, dict(resp.getheaders())
        return data
    raise HttpError(503, f"{method} {url}: retries exhausted")


def http_get_with_headers(url: str, timeout: float = 30.0,
                          headers: Optional[dict] = None):
    """Cluster GET returning (body, response headers) — for callers
    that need metadata the body doesn't carry (stored filename in
    Content-Disposition, etags, Content-Range on ranged reads)."""
    url = _client_url(url)
    try:
        return _pooled_call("GET", url, None, headers or {}, timeout,
                            want_headers=True)
    except HttpError:
        raise
    except (OSError, _httpc.HTTPException) as e:
        raise HttpError(503, f"GET {url}: {e}") from None


def http_call(method: str, url: str, body: bytes = None,
              headers: dict = None, timeout: float = 30.0,
              external: bool = False) -> bytes:
    """``external=True`` marks a non-cluster endpoint (webhooks, third
    parties): the URL keeps its scheme and https uses the default
    verified context — the cluster TLS rewrite must not break plain-HTTP
    externals nor weaken hostname checks on real ones. Cluster calls go
    through the keep-alive pool."""
    if not external:
        url = _client_url(url)
        try:
            return _pooled_call(method, url, body, headers or {},
                                timeout)
        except HttpError:
            raise
        except (OSError, _httpc.HTTPException) as e:
            raise HttpError(503, f"{method} {url}: {e}") from None
    req = urllib.request.Request(url, data=body, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=None) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        detail = e.read().decode("utf-8", "replace")[:500]
        raise HttpError(e.code, f"{method} {url}: {detail}") from None
    except (urllib.error.URLError, socket.timeout, ConnectionError) as e:
        raise HttpError(503, f"{method} {url}: {e}") from None


def http_download(url: str, path: str, timeout: float = 600.0) -> int:
    """Stream a GET response straight to a file (volume-sized pulls must
    not transit RAM). Returns bytes written."""
    url = _client_url(url)
    req = urllib.request.Request(url, method="GET",
                                 headers=_traced_headers(None))
    try:
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=_TLS["client_ctx"]) as resp, \
                open(path, "wb") as out:
            total = 0
            while True:
                chunk = resp.read(1 << 20)
                if not chunk:
                    return total
                out.write(chunk)
                total += len(chunk)
    except urllib.error.HTTPError as e:
        detail = e.read().decode("utf-8", "replace")[:500]
        raise HttpError(e.code, f"GET {url}: {detail}") from None
    except (urllib.error.URLError, socket.timeout, ConnectionError) as e:
        raise HttpError(503, f"GET {url}: {e}") from None


def get_json(url: str, timeout: float = 30.0) -> dict:
    return json.loads(http_call("GET", url, timeout=timeout) or b"{}")


def post_json(url: str, obj=None, timeout: float = 30.0) -> dict:
    body = json.dumps(obj or {}).encode()
    out = http_call("POST", url, body,
                    {"Content-Type": "application/json"}, timeout)
    return json.loads(out or b"{}")


def post_chunked(url: str, chunks, headers: Optional[dict] = None,
                 timeout: float = 300.0) -> bytes:
    """POST an iterable of byte chunks with chunked transfer-encoding —
    the body can start flowing before its total size is known (the EC
    spread pushes shard ranges as the encode produces them). Chunked
    bodies are not replayable, so the call always goes out on a fresh
    connection and is never retried here; the spread layer owns retry."""
    url = _client_url(url)
    h = dict(headers or {})
    h["Transfer-Encoding"] = "chunked"
    try:
        return _pooled_call("POST", url, iter(chunks), h, timeout,
                            encode_chunked=True)
    except HttpError:
        raise
    except (OSError, _httpc.HTTPException) as e:
        raise HttpError(503, f"POST {url}: {e}") from None


def _quote_name(name: str) -> str:
    """Escape a filename for a quoted-string header parameter."""
    return name.replace("\\", "\\\\").replace('"', '\\"')


def post_multipart(url: str, filename: str, data: bytes,
                   content_type: str = "application/octet-stream",
                   timeout: float = 60.0,
                   headers: dict = None) -> dict:
    boundary = uuid.uuid4().hex
    body = (f"--{boundary}\r\n"
            f'Content-Disposition: form-data; name="file"; '
            f'filename="{_quote_name(filename or "file")}"\r\n'
            f"Content-Type: {content_type}\r\n\r\n").encode() \
        + data + f"\r\n--{boundary}--\r\n".encode()
    all_headers = {"Content-Type":
                   f"multipart/form-data; boundary={boundary}"}
    all_headers.update(headers or {})
    out = http_call("POST", url, body, all_headers, timeout)
    return json.loads(out or b"{}")


class _ChainReader:
    """read()-able concatenation of byte segments and file objects with
    a known total length — streams a multipart body without building it."""

    def __init__(self, parts):
        self.parts = []
        self.len = 0
        import io as _io
        for p in parts:
            if isinstance(p, bytes):
                self.parts.append(_io.BytesIO(p))
                self.len += len(p)
            else:
                f, size = p
                self.parts.append(f)
                self.len += size
        self.i = 0

    def __len__(self):
        return self.len

    def read(self, n: int = -1) -> bytes:
        out = b""
        while self.i < len(self.parts):
            chunk = self.parts[self.i].read(n if n >= 0 else (1 << 20))
            if chunk:
                out += chunk
                if n >= 0:
                    return out
            else:
                self.i += 1
        return out


def post_multipart_file(url: str, filename: str, fileobj, size: int,
                        content_type: str = "application/octet-stream",
                        timeout: float = 600.0,
                        headers: dict = None) -> dict:
    """post_multipart for file-likes: the body streams, so a
    volume-sized upload never transits RAM whole."""
    boundary = uuid.uuid4().hex
    prologue = (f"--{boundary}\r\n"
                f'Content-Disposition: form-data; name="file"; '
                f'filename="{_quote_name(filename or "file")}"\r\n'
                f"Content-Type: {content_type}\r\n\r\n").encode()
    epilogue = f"\r\n--{boundary}--\r\n".encode()
    body = _ChainReader([prologue, (fileobj, size), epilogue])
    all_headers = {
        "Content-Type": f"multipart/form-data; boundary={boundary}",
        "Content-Length": str(len(body)),
    }
    all_headers.update(headers or {})
    out = http_call("POST", url, body, all_headers, timeout)
    return json.loads(out or b"{}")
