"""FilerServer — HTTP file API over the filer metadata layer.

Reference weed/server/filer_server*.go:
- GET streams chunk views from volume servers (filer_server_handlers_read)
- POST auto-chunks large uploads: per chunk assign fid from master ->
  upload to a volume server -> CreateEntry
  (filer_server_handlers_write_autochunk.go:23-186)
- DELETE removes entries (recursive with ?recursive=true) and deletes
  the chunks behind them (filer_server_handlers_write.go)
- /filer/events long-poll = ListenForEvents / `weed watch`
  (filer_grpc_server.go SubscribeMetadata analog)
"""

from __future__ import annotations

import posixpath
import queue
import threading
import time
from typing import Optional

from ..client import operation
from ..filer import Attr, Entry, Filer
from ..filer.filer import FilerError, NotFoundError
from ..filer.log_buffer import LogBuffer, event_notification
from ..filer.filerstore import make_store
from ..filer.stream import read_chunked
from ..util import tracing
from .http_util import (HttpError, HttpServer, Request, Response,
                        Router, profile_handler, traces_export_handler,
                        traces_handler)

CHUNK_SIZE_DEFAULT = 32 << 20  # reference -maxMB=32 autochunk default


class FilerServer:
    def __init__(self, port: int = 8888, host: str = "127.0.0.1",
                 master_url: str = "127.0.0.1:9333",
                 store: str = "memory", store_options: Optional[dict] = None,
                 collection: str = "", replication: str = "",
                 chunk_size: int = CHUNK_SIZE_DEFAULT,
                 notify_publisher=None, jwt_signing_key: str = "",
                 cipher: bool = False, compress: bool = False):
        router = Router()
        router.add("GET", "/filer/events", self.events_handler)
        router.add("GET", "/filer/status", self.status_handler)
        # metadata API — the analog of the reference's SeaweedFiler gRPC
        # service (weed/pb/filer.proto:10-45: LookupDirectoryEntry,
        # ListEntries, CreateEntry, UpdateEntry, DeleteEntry,
        # AtomicRenameEntry); lets gateways (s3/webdav/mount) run in a
        # separate process against this filer
        router.add("GET", "/filer/meta/lookup", self.meta_lookup)
        router.add("GET", "/filer/meta/list", self.meta_list)
        router.add("POST", "/filer/meta/create", self.meta_create)
        router.add("POST", "/filer/meta/update", self.meta_update)
        router.add("POST", "/filer/meta/delete", self.meta_delete)
        router.add("POST", "/filer/meta/rename", self.meta_rename)
        router.add("POST", "/filer/meta/delete_chunks",
                   self.meta_delete_chunks)
        router.add("GET", "/metrics", self.metrics_handler)
        router.add("GET", "/stats/integrity", self.stats_integrity)
        router.add("GET", "/admin/traces", traces_handler)
        router.add("GET", "/admin/traces/export", traces_export_handler)
        router.add("POST", "/admin/profile", profile_handler)
        router.set_fallback(self.data_handler)
        from ..stats.metrics import (FILER_REQUEST_COUNTER,
                                     FILER_REQUEST_HISTOGRAM)

        def observe(label, seconds, ok):
            FILER_REQUEST_COUNTER.inc(label if ok else label + " error")
            FILER_REQUEST_HISTOGRAM.observe(
                seconds, label, trace_id=tracing.current_trace_id())
        router.observe = observe
        self.server = HttpServer(port, router, host)
        self.port = self.server.port
        self.host = host
        router.node = f"{host}:{self.port}"
        self.master_url = master_url
        self.collection = collection
        self.replication = replication
        self.chunk_size = chunk_size
        self.cipher = cipher
        self.compress = compress
        self.jwt_signing_key = jwt_signing_key
        self.filer = Filer(make_store(store, **(store_options or {})))
        self.log_buffer = LogBuffer()
        self.notify_publisher = notify_publisher
        self.filer.on_update(self._on_meta_update)
        self.vid_cache = operation.VidCache(master_url, watch=True)
        self._fetch = None
        self._stop = threading.Event()
        self._deleter = threading.Thread(target=self._deletion_loop,
                                         daemon=True,
                                         name="filer-deleter")
        self._notify_queue: queue.Queue = queue.Queue(maxsize=1024)
        self._notifier = threading.Thread(target=self._notify_loop,
                                          daemon=True,
                                          name="filer-notifier") \
            if notify_publisher is not None else None

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        self.server.start()
        self._deleter.start()
        if self._notifier is not None:
            self._notifier.start()
        return self

    def stop(self):
        self._stop.set()
        if self._notifier is not None:
            try:
                self._notify_queue.put_nowait(None)  # drain sentinel
            except queue.Full:
                try:  # make room: shutdown outranks a pending event
                    self._notify_queue.get_nowait()
                except queue.Empty:
                    pass
                try:
                    self._notify_queue.put_nowait(None)
                except queue.Full:
                    pass  # notifier is daemon; process exit reaps it
        self.log_buffer.close()
        self.server.stop()
        self.filer.store.close()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    def _on_meta_update(self, old, new, delete_chunks):
        event = event_notification(old, new, delete_chunks)
        self.log_buffer.append(event)
        if self.notify_publisher is not None:
            # external brokers are slow/fallible and the mutation has
            # already committed — dispatch off the write path, never
            # fail the client. Bounded drop-oldest buffer: a dead
            # endpoint under sustained ingest must not grow an
            # unbounded backlog of stale events (the durable record is
            # the metadata event log; this channel is best-effort).
            key = (new or old).full_path
            try:
                self._notify_queue.put_nowait((key, event))
            except queue.Full:
                from ..util import glog
                try:
                    dropped = self._notify_queue.get_nowait()
                except queue.Empty:  # raced a drain
                    dropped = None
                if dropped is None and self._stop.is_set():
                    # popped the shutdown sentinel: put it back, the
                    # notifier must still exit
                    self._notify_queue.put_nowait(None)
                    return
                if dropped is not None:
                    glog.V(0).infof("notification buffer full; dropped "
                                    "event for %s", dropped[0])
                try:
                    self._notify_queue.put_nowait((key, event))
                except queue.Full:  # raced a refill: drop the new event
                    glog.V(0).infof("notification buffer full; dropped "
                                    "event for %s", key)

    def _notify_loop(self):
        from ..util import glog
        while True:
            item = self._notify_queue.get()
            if item is None:
                return
            key, event = item
            try:
                self.notify_publisher.send(key, event)
            except Exception as e:  # noqa: BLE001 - must not kill the loop
                glog.V(0).infof("notification for %s failed: %s", key, e)

    def _deletion_loop(self):
        """Drain the filer's chunk-deletion queue against the cluster
        (reference filer_deletion.go loopProcessingDeletion)."""
        from ..util import config
        while not self._stop.wait(
                max(0.01, config.env_float("SW_FILER_TICK_S"))):
            self.flush_deletions()

    def flush_deletions(self):
        for fid in self.filer.drain_deletion_queue():
            try:
                jwt = ""
                if self.jwt_signing_key:
                    from ..security.jwt import GenJwt
                    jwt = GenJwt(self.jwt_signing_key, fid)
                operation.delete_file(self.master_url, fid,
                                      self.vid_cache, jwt=jwt)
            except HttpError:
                pass

    # -- handlers -----------------------------------------------------------

    def metrics_handler(self, req: Request):
        from ..stats.metrics import FILER_GATHER
        return Response(FILER_GATHER.render().encode(),
                        content_type="text/plain; version=0.0.4")

    def status_handler(self, req: Request):
        return {"version": "seaweedfs-tpu", "master": self.master_url}

    def stats_integrity(self, req: Request):
        """Data-integrity view for filer clients: the master's repair
        queue (open incidents, time-to-re-protection), so an S3/filer
        operator sees durability exposure without master access."""
        import json as _json
        from .http_util import http_call
        out = http_call(
            "GET", f"http://{self.master_url}/cluster/repairs", timeout=10)
        return _json.loads(out or b"{}")

    def events_handler(self, req: Request):
        since = float(req.query.get("since", 0) or 0)
        timeout = min(float(req.query.get("timeout", 10) or 10), 55.0)
        # server-side path filter like the reference's ListenForEvents
        # PathPrefix (weed/command/watch.go -pathPrefix): a subscriber
        # watching /buckets/x must not pay for the whole event stream
        prefix = req.query.get("prefix", "")
        # component-boundary matching: /data must cover /data itself
        # (deletes/chmods of the watched root) and /data/x, but never
        # the sibling tree /database
        base = prefix.rstrip("/")

        def touches(e: dict) -> bool:
            # an event matches if EITHER side of the mutation lives
            # under the prefix (a rename out of the watched tree must
            # still reach the subscriber as its delete half)
            for side in ("newEntry", "oldEntry"):
                ent = e.get(side)
                if not ent:
                    continue
                path = str(ent.get("path", ""))
                if path == base or path.startswith(base + "/"):
                    return True
            return False

        # cursor = the scanned high-water mark. Without it, a batch
        # that the prefix filter empties would leave the client's
        # `since` untouched and the next long-poll would return (and
        # refilter) the same events immediately — a busy loop. And
        # when the filter empties a batch mid-timeout, keep waiting
        # server-side: a /quiet watcher on a filer ingesting a heavy
        # foreign stream must not pay one round trip per foreign batch
        deadline = time.monotonic() + timeout
        while True:
            remaining = max(0.0, deadline - time.monotonic())
            events = self.log_buffer.wait_since(since, timeout=remaining)
            cursor = max((t for t, _ in events), default=since)
            if prefix and events:
                events = [(t, e) for t, e in events if touches(e)]
                if not events and deadline - time.monotonic() > 0:
                    since = cursor
                    continue
            return {"cursor": cursor,
                    "events": [{"ts": t, "event": e}
                               for t, e in events]}

    def data_handler(self, req: Request):
        # normpath strips the trailing slash, which carries meaning for
        # writes ("upload into this directory") — capture it first
        is_dir_path = req.path.endswith("/") and req.path != "/"
        path = posixpath.normpath(req.path)
        if req.method in ("GET", "HEAD"):
            return self.read_handler(req, path)
        if req.method in ("POST", "PUT"):
            if "mv.to" in req.query:
                return self.move_handler(req, path)
            return self.write_handler(req, path, is_dir_path)
        if req.method == "DELETE":
            return self.delete_handler(req, path)
        raise HttpError(405, req.method)

    def read_handler(self, req: Request, path: str):
        try:
            entry = self.filer.find_entry(path)
        except NotFoundError:
            raise HttpError(404, f"{path} not found") from None
        if entry.is_directory:
            return self.list_handler(req, path)
        size = entry.size()
        offset, length, status = 0, size, 200
        headers = {"Accept-Ranges": "bytes"}
        from .http_util import parse_range
        parsed = parse_range(req.headers.get("Range", ""), size)
        if parsed is not None:
            offset, length = parsed
            headers["Content-Range"] = \
                f"bytes {offset}-{offset+length-1}/{size}"
            status = 206
        head = req.method == "HEAD"
        body = b"" if head else read_chunked(entry.chunks, offset, length,
                                             self._chunk_fetcher())
        mime = entry.attr.mime or "application/octet-stream"
        if entry.attr.md5:
            headers["Etag"] = f'"{entry.attr.md5}"'
        headers["Last-Modified"] = time.strftime(
            "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(entry.attr.mtime))
        return Response(body, status, mime, headers,
                        content_length=length if head else None)

    def _chunk_fetcher(self):
        if self._fetch is None:
            from ..filer.stream import default_fetcher
            self._fetch = default_fetcher(self.master_url)
        return self._fetch

    def list_handler(self, req: Request, path: str):
        limit = int(req.query.get("limit", 1000))
        last = req.query.get("lastFileName", "")
        entries = self.filer.list_entries(path, last, False, limit)
        # browsers get the file-browser page (reference filer_ui/);
        # API clients keep the JSON listing
        if "text/html" in req.headers.get("Accept", "") and \
                req.query.get("pretty") != "y":
            import html as _html
            import urllib.parse as _up
            from .status_ui import Raw, render_page
            rows = []
            base = path.rstrip("/")
            for e in entries:
                href = _up.quote(f"{base}/{e.name}")
                name = _html.escape(e.name)
                kind = "dir" if e.is_directory else (e.attr.mime or "file")
                rows.append((Raw(f'<a href="{href}">{name}</a>'), kind,
                             e.size() if not e.is_directory else "-"))
            footer = ""
            if entries and len(entries) == limit:  # page truncated
                nxt = _up.quote(entries[-1].name)
                footer = (f'<p><a href="?lastFileName={nxt}&'
                          f'limit={limit}">next page &raquo;</a></p>')
            page = render_page(
                f"Filer {path}",
                [(path, ["name", "type", "size"], rows)],
                footer_html=footer)
            return Response(page,
                            content_type="text/html; charset=utf-8")
        return {
            "path": path,
            "entries": [self._entry_json(e) for e in entries],
            "lastFileName": entries[-1].name if entries else "",
            "shouldDisplayLoadMore": len(entries) == limit,
        }

    @staticmethod
    def _entry_json(e: Entry) -> dict:
        from ..filer.entry import entry_to_wire
        d = entry_to_wire(e)
        d["FileSize"] = e.size()
        return d

    def write_handler(self, req: Request, path: str,
                      is_dir_path: bool = False):
        filename, ctype, data = req.upload_payload()
        if is_dir_path and filename:
            # POST /dir/ with a file: store as /dir/<filename>
            path = posixpath.join(path, filename)
        elif is_dir_path or req.query.get("op") == "mkdir":
            from ..filer.entry import new_dir_entry
            self.filer.create_entry(new_dir_entry(path))
            return {"name": posixpath.basename(path)}
        collection = req.query.get("collection", self.collection)
        replication = req.query.get("replication", self.replication)
        ttl = req.query.get("ttl", "")
        from ..filer.upload import split_and_upload
        chunks, md5_hex = split_and_upload(
            self.master_url, data, posixpath.basename(path),
            self.chunk_size, collection=collection,
            replication=replication, ttl=ttl,
            content_type=ctype or "application/octet-stream",
            cipher=self.cipher, compress=self.compress)
        now = time.time()
        # reference ?mode= (octal file mode, default 0660 —
        # filer_server_handlers_write.go:156)
        try:
            mode = int(req.query.get("mode", "") or "660", 8)
            # negatives parse in Python (unlike the reference's
            # ParseUint): treat them as invalid too
            mode = mode & 0o7777 if mode >= 0 else 0o660
        except ValueError:
            mode = 0o660
        attr = Attr(mtime=now, crtime=now, mime=ctype, mode=mode,
                    collection=collection, replication=replication,
                    ttl_sec=_ttl_seconds(ttl), md5=md5_hex)
        entry = Entry(full_path=path, attr=attr, chunks=chunks)
        try:
            self.filer.create_entry(entry)
        except FilerError as e:
            raise HttpError(409, str(e)) from None
        return {"name": posixpath.basename(path), "size": len(data),
                "fid": chunks[0].fid if chunks else ""}

    def move_handler(self, req: Request, path: str):
        dest = req.query["mv.to"]
        try:
            self.filer.rename_entry(path, dest)
        except NotFoundError:
            raise HttpError(404, f"{path} not found") from None
        except FilerError as e:
            raise HttpError(409, str(e)) from None
        return {"from": path, "to": dest}

    # -- metadata API (gateway-facing; see routes above) --------------------

    @staticmethod
    def _entry_from_json(d: dict) -> Entry:
        from ..filer.entry import entry_from_wire
        return entry_from_wire(d)

    def meta_lookup(self, req: Request):
        path = posixpath.normpath(req.query.get("path", "/"))
        try:
            return {"entry": self._entry_json(self.filer.find_entry(path))}
        except NotFoundError:
            raise HttpError(404, f"{path} not found") from None

    def meta_list(self, req: Request):
        path = posixpath.normpath(req.query.get("path", "/"))
        limit = int(req.query.get("limit", 1000))
        last = req.query.get("lastFileName", "")
        inclusive = req.query.get("inclusive", "") == "true"
        entries = self.filer.list_entries(path, last, inclusive, limit)
        return {"entries": [self._entry_json(e) for e in entries]}

    def meta_create(self, req: Request):
        entry = self._entry_from_json(req.json()["entry"])
        try:
            self.filer.create_entry(entry)
        except FilerError as e:
            raise HttpError(409, str(e)) from None
        return {"name": entry.name}

    def meta_update(self, req: Request):
        entry = self._entry_from_json(req.json()["entry"])
        try:
            self.filer.update_entry(entry)
        except NotFoundError:
            raise HttpError(404, f"{entry.full_path} not found") from None
        return {"name": entry.name}

    def meta_delete(self, req: Request):
        body = req.json()
        try:
            self.filer.delete_entry(
                posixpath.normpath(body["path"]),
                recursive=body.get("recursive", False),
                ignore_recursive_error=body.get("ignoreRecursiveError",
                                                False))
        except NotFoundError:
            raise HttpError(404, f"{body['path']} not found") from None
        except FilerError as e:
            raise HttpError(409, str(e)) from None
        return {}

    def meta_rename(self, req: Request):
        body = req.json()
        try:
            self.filer.rename_entry(posixpath.normpath(body["old"]),
                                    posixpath.normpath(body["new"]))
        except NotFoundError:
            raise HttpError(404, f"{body['old']} not found") from None
        except FilerError as e:
            raise HttpError(409, str(e)) from None
        return {}

    def meta_delete_chunks(self, req: Request):
        from ..filer.entry import FileChunk
        chunks = [FileChunk.from_dict(c)
                  for c in req.json().get("chunks", [])]
        self.filer.queue_chunk_deletion(chunks)
        return {}

    def delete_handler(self, req: Request, path: str):
        recursive = req.query.get("recursive", "") == "true"
        ignore_err = req.query.get("ignoreRecursiveError", "") == "true"
        # reference ?skipChunkDeletion=true: drop metadata only
        keep_chunks = req.query.get("skipChunkDeletion", "") == "true"
        try:
            self.filer.delete_entry(path, recursive=recursive,
                                    ignore_recursive_error=ignore_err,
                                    delete_chunks=not keep_chunks)
        except NotFoundError:
            raise HttpError(404, f"{path} not found") from None
        except FilerError as e:
            raise HttpError(409, str(e)) from None
        return Response(b"", 204)


def _ttl_seconds(ttl: str) -> int:
    from ..storage.types import TTL
    return TTL.parse(ttl).minutes * 60 if ttl else 0
