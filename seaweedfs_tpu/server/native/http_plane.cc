// Native volume-server read plane.
//
// The reference's data plane is Go: goroutine-per-connection HTTP serving
// needle reads straight off the volume files (reference
// weed/server/volume_server_handlers_read.go). The Python server keeps
// full semantics but is GIL-bound (~2.7k reads/s/process); this library
// is the native equivalent of the reference's hot read loop: a
// thread-per-connection keep-alive HTTP/1.1 server that parses
// `GET /<vid>,<fid>`, looks the needle up in an in-process index mirror
// (synced from Python over ctypes), preads the needle blob, validates
// cookie/CRC/TTL, and answers — no Python in the loop.
//
// Scope is the FAST PATH only. Anything with semantics beyond a plain
// stored needle — gzip-stored payloads, chunk manifests, Seaweed-* pair
// headers, image resize queries, EC volumes, remote volumes — is answered
// with a 307 redirect to the Python server (`fallback`), which remains
// the source of truth. Correctness parity for the served cases is pinned
// by tests/test_native_plane.py against the Python responses.
//
// Needle layout parsed here == storage/needle.py (byte-compatible with
// reference weed/storage/needle/needle_read_write.go):
//   header: Cookie(4) Id(8) Size(4) big-endian
//   v2/v3 body: DataSize(4) Data Flags(1) [Name] [Mime] [LastModified(5)]
//               [TTL(2)] [PairsSize(2) Pairs] CRC(4) [AppendAtNs(8)] pad8
// CRC is masked Castagnoli over Data (reference crc.go:25).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <fcntl.h>

namespace {

// ---------------------------------------------------------------- crc32c
struct CrcTables {
  uint32_t t[8][256];
  CrcTables() {
    const uint32_t poly = 0x82F63B78u;
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
      t[0][i] = c;
    }
    for (int j = 1; j < 8; j++)
      for (uint32_t i = 0; i < 256; i++)
        t[j][i] = t[j - 1][i] >> 8 ^ t[0][t[j - 1][i] & 0xFF];
  }
};
const CrcTables g_crc;

uint32_t crc32c(const uint8_t* data, size_t n) {
  uint32_t crc = ~0u;
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    crc ^= static_cast<uint32_t>(data[i]) |
           (static_cast<uint32_t>(data[i + 1]) << 8) |
           (static_cast<uint32_t>(data[i + 2]) << 16) |
           (static_cast<uint32_t>(data[i + 3]) << 24);
    crc = g_crc.t[7][crc & 0xFF] ^ g_crc.t[6][(crc >> 8) & 0xFF] ^
          g_crc.t[5][(crc >> 16) & 0xFF] ^ g_crc.t[4][crc >> 24] ^
          g_crc.t[3][data[i + 4]] ^ g_crc.t[2][data[i + 5]] ^
          g_crc.t[1][data[i + 6]] ^ g_crc.t[0][data[i + 7]];
  }
  for (; i < n; i++) crc = g_crc.t[0][(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

uint32_t masked_crc(uint32_t crc) {  // reference crc.go:25
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// --------------------------------------------------------------- needles
constexpr int kHeaderSize = 16;
constexpr int kChecksumSize = 4;
constexpr int kTimestampSize = 8;
constexpr int kPaddingSize = 8;
constexpr uint32_t kTombstoneSize = 0xFFFFFFFFu;

constexpr uint8_t kFlagGzip = 0x01;
constexpr uint8_t kFlagHasName = 0x02;
constexpr uint8_t kFlagHasMime = 0x04;
constexpr uint8_t kFlagHasLastModified = 0x08;
constexpr uint8_t kFlagHasTtl = 0x10;
constexpr uint8_t kFlagHasPairs = 0x20;
constexpr uint8_t kFlagChunkManifest = 0x80;

uint64_t be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = v << 8 | p[i];
  return v;
}
uint32_t be32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) << 24 | static_cast<uint32_t>(p[1]) << 16 |
         static_cast<uint32_t>(p[2]) << 8 | p[3];
}

int64_t actual_size(uint32_t size, int version) {
  int64_t base = kHeaderSize + static_cast<int64_t>(size) + kChecksumSize;
  if (version == 3) base += kTimestampSize;
  // reference PaddingLength never returns 0 (needle_read_write.go:287)
  return base + (kPaddingSize - base % kPaddingSize);
}

// minutes per TTL unit (storage/types.py _UNIT_MINUTES)
int64_t ttl_minutes(uint8_t count, uint8_t unit) {
  static const int64_t per[] = {0, 1, 60, 1440, 10080, 44640, 525600};
  return unit < 7 ? count * per[unit] : 0;
}

struct ParsedNeedle {
  uint32_t cookie = 0;
  uint64_t id = 0;
  uint32_t size = 0;
  const uint8_t* data = nullptr;  // into the read buffer
  uint32_t data_size = 0;
  uint8_t flags = 0;
  std::string name, mime;
  int64_t last_modified = 0;  // unix seconds
  uint8_t ttl_count = 0, ttl_unit = 0;
  uint32_t checksum = 0;  // stored masked crc
};

// Returns 0 ok, -1 corrupt.
int parse_needle(const uint8_t* blob, size_t len, int version,
                 ParsedNeedle* out) {
  if (len < kHeaderSize) return -1;
  out->cookie = be32(blob);
  out->id = be64(blob + 4);
  out->size = be32(blob + 12);
  size_t size = out->size;
  if (kHeaderSize + size + kChecksumSize > len) return -1;
  const uint8_t* b = blob + kHeaderSize;
  if (version == 1) {
    out->data = b;
    out->data_size = out->size;
    out->flags = 0;
  } else {
    // v2/v3 body of `size` bytes
    size_t idx = 0;
    if (size > 0) {
      if (idx + 4 > size) return -1;
      out->data_size = be32(b + idx);
      idx += 4;
      if (idx + out->data_size >= size) return -1;  // flags byte must follow
      out->data = b + idx;
      idx += out->data_size;
      out->flags = b[idx++];
    }
    if (idx < size && (out->flags & kFlagHasName)) {
      uint8_t n = b[idx++];
      if (idx + n > size) return -1;
      out->name.assign(reinterpret_cast<const char*>(b + idx), n);
      idx += n;
    }
    if (idx < size && (out->flags & kFlagHasMime)) {
      uint8_t n = b[idx++];
      if (idx + n > size) return -1;
      out->mime.assign(reinterpret_cast<const char*>(b + idx), n);
      idx += n;
    }
    if (idx < size && (out->flags & kFlagHasLastModified)) {
      if (idx + 5 > size) return -1;
      int64_t v = 0;
      for (int i = 0; i < 5; i++) v = v << 8 | b[idx + i];
      out->last_modified = v;
      idx += 5;
    }
    if (idx < size && (out->flags & kFlagHasTtl)) {
      if (idx + 2 > size) return -1;
      out->ttl_count = b[idx];
      out->ttl_unit = b[idx + 1];
      idx += 2;
    }
  }
  out->checksum = be32(b + size);
  return 0;
}

// ---------------------------------------------------------------- server
struct VolumeRec {
  int fd = -1;
  int version = 3;
  std::unordered_map<uint64_t, std::pair<uint64_t, uint32_t>> index;
  mutable std::shared_mutex mu;
  ~VolumeRec() {
    if (fd >= 0) close(fd);
  }
};

struct Server {
  int listen_fd = -1;
  uint16_t port = 0;
  std::string fallback;  // host:port of the Python server
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> served{0}, redirected{0}, errors{0};
  std::atomic<int> live{0};
  int max_conns = 1024;
  int64_t max_fastpath_bytes = 64ll << 20;
  std::thread acceptor;
  std::unordered_map<uint32_t, std::shared_ptr<VolumeRec>> vols;
  mutable std::shared_mutex vols_mu;

  std::shared_ptr<VolumeRec> find(uint32_t vid) const {
    std::shared_lock<std::shared_mutex> l(vols_mu);
    auto it = vols.find(vid);
    return it == vols.end() ? nullptr : it->second;
  }
};

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

// header+body in one syscall (syscalls dominate small-needle serving)
bool send_two(int fd, const void* a, size_t an, const void* b, size_t bn) {
  struct iovec iov[2] = {{const_cast<void*>(a), an},
                         {const_cast<void*>(b), bn}};
  size_t idx = 0;
  while (idx < 2) {
    ssize_t w = writev(fd, iov + idx, static_cast<int>(2 - idx));
    if (w <= 0) return false;
    size_t done = static_cast<size_t>(w);
    while (idx < 2 && done >= iov[idx].iov_len) {
      done -= iov[idx].iov_len;
      idx++;
    }
    if (idx < 2 && done > 0) {
      iov[idx].iov_base = static_cast<char*>(iov[idx].iov_base) + done;
      iov[idx].iov_len -= done;
    }
  }
  return true;
}

struct Request {
  std::string method, target;
  bool keepalive = true;
  bool http10 = false;
  std::string if_none_match, range, if_modified_since;
  int64_t content_length = 0;
  bool chunked = false;
};

// Reads one request off the socket (blocking). Returns 1 ok, 0 clean EOF,
// -1 error/overflow.
int read_request(int fd, std::string* acc, Request* out) {
  // acc may already hold pipelined bytes from the previous read
  size_t scanned = 0;
  for (;;) {
    size_t pos = acc->find("\r\n\r\n", scanned > 3 ? scanned - 3 : 0);
    if (pos != std::string::npos) {
      std::string head = acc->substr(0, pos);
      acc->erase(0, pos + 4);
      // request line
      size_t sp1 = head.find(' ');
      size_t sp2 = head.find(' ', sp1 + 1);
      size_t eol = head.find("\r\n");
      if (sp1 == std::string::npos || sp2 == std::string::npos ||
          sp2 > (eol == std::string::npos ? head.size() : eol))
        return -1;
      out->method = head.substr(0, sp1);
      out->target = head.substr(sp1 + 1, sp2 - sp1 - 1);
      out->http10 = head.compare(sp2 + 1, 8, "HTTP/1.0") == 0;
      out->keepalive = !out->http10;
      // headers we care about
      size_t ls = (eol == std::string::npos) ? head.size() : eol + 2;
      while (ls < head.size()) {
        size_t le = head.find("\r\n", ls);
        if (le == std::string::npos) le = head.size();
        size_t colon = head.find(':', ls);
        if (colon != std::string::npos && colon < le) {
          std::string k = head.substr(ls, colon - ls);
          size_t vs = colon + 1;
          while (vs < le && head[vs] == ' ') vs++;
          std::string v = head.substr(vs, le - vs);
          for (auto& c : k) c = static_cast<char>(tolower(c));
          if (k == "connection") {
            std::string lv = v;
            for (auto& c : lv) c = static_cast<char>(tolower(c));
            if (lv.find("close") != std::string::npos) out->keepalive = false;
            if (out->http10 && lv.find("keep-alive") != std::string::npos)
              out->keepalive = true;
          } else if (k == "if-none-match") {
            out->if_none_match = v;
          } else if (k == "if-modified-since") {
            out->if_modified_since = v;
          } else if (k == "range") {
            out->range = v;
          } else if (k == "content-length") {
            char* end = nullptr;
            out->content_length = strtoll(v.c_str(), &end, 10);
            if (out->content_length < 0 || (end && *end != '\0'))
              out->content_length = 0;
          } else if (k == "transfer-encoding") {
            out->chunked = true;  // no body framing here: close after
          }
        }
        ls = le + 2;
      }
      return 1;
    }
    if (acc->size() > 16384) return -1;  // header cap
    scanned = acc->size();
    char buf[4096];
    ssize_t r = recv(fd, buf, sizeof buf, 0);
    if (r == 0) return acc->empty() ? 0 : -1;
    if (r < 0) return -1;
    acc->append(buf, static_cast<size_t>(r));
  }
}

void respond_simple(int fd, int code, const char* reason,
                    const std::string& body, bool keepalive,
                    const std::string& extra_headers = "",
                    const char* ctype = "text/plain") {
  std::string head = "HTTP/1.1 " + std::to_string(code) + " " + reason +
                     "\r\nContent-Length: " + std::to_string(body.size()) +
                     "\r\nContent-Type: " + ctype + "\r\n" + extra_headers +
                     "Connection: " +
                     (keepalive ? "keep-alive" : "close") + "\r\n\r\n";
  if (body.empty())
    send_all(fd, head.data(), head.size());
  else
    send_two(fd, head.data(), head.size(), body.data(), body.size());
}

void redirect_to_fallback(Server* s, int fd, const Request& req) {
  s->redirected++;
  std::string loc = "http://" + s->fallback + req.target;
  std::string hdr = "Location: " + loc + "\r\n";
  // 307 preserves method+body; our fallback is the authoritative server
  respond_simple(fd, 307, "Temporary Redirect", "", req.keepalive, hdr);
}

// `%xx` unescape for the path (fids are plain hex, but be tolerant)
std::string unescape(const std::string& in) {
  std::string out;
  out.reserve(in.size());
  for (size_t i = 0; i < in.size(); i++) {
    if (in[i] == '%' && i + 2 < in.size() && isxdigit(in[i + 1]) &&
        isxdigit(in[i + 2])) {
      out.push_back(static_cast<char>(
          strtol(in.substr(i + 1, 2).c_str(), nullptr, 16)));
      i += 2;
    } else {
      out.push_back(in[i]);
    }
  }
  return out;
}

// Parse "/<vid>,<keyhex><cookie8>" (also '/' separator). Returns false if
// the target is not a plain fid path (query string, extension, etc).
bool parse_fid_path(const std::string& target, uint32_t* vid, uint64_t* key,
                    uint32_t* cookie) {
  if (target.empty() || target[0] != '/') return false;
  if (target.find('?') != std::string::npos) return false;
  std::string p = unescape(target.substr(1));
  size_t sep = p.find(',');
  if (sep == std::string::npos) sep = p.find('/');
  if (sep == std::string::npos || sep == 0) return false;
  uint64_t v = 0;
  for (size_t i = 0; i < sep; i++) {
    if (!isdigit(p[i])) return false;
    v = v * 10 + static_cast<uint64_t>(p[i] - '0');
    if (v > 0xFFFFFFFFull) return false;
  }
  std::string kh = p.substr(sep + 1);
  // mirror storage/types.py parse_key_hash: 8 < len <= 24, last 8 hex
  // chars are the cookie
  if (kh.size() <= 8 || kh.size() > 24) return false;
  for (char c : kh)
    if (!isxdigit(c)) return false;
  if (kh.size() % 2) kh = "0" + kh;
  uint64_t k = 0;
  for (size_t i = 0; i + 8 < kh.size(); i++)
    k = k << 4 | static_cast<uint64_t>(strtol(kh.substr(i, 1).c_str(),
                                              nullptr, 16));
  uint32_t ck = static_cast<uint32_t>(
      strtoul(kh.substr(kh.size() - 8).c_str(), nullptr, 16));
  *vid = static_cast<uint32_t>(v);
  *key = k;
  *cookie = ck;
  return true;
}

// Single-range parse: "bytes=a-b" / "bytes=a-" / "bytes=-n" (mirrors
// server/http_util.parse_range; multi-range -> not handled -> full body)
bool all_digits(const std::string& s) {
  if (s.empty()) return false;
  for (char c : s)
    if (!isdigit(static_cast<unsigned char>(c))) return false;
  return true;
}

bool parse_range_header(const std::string& r, int64_t total, int64_t* start,
                        int64_t* length) {
  if (r.compare(0, 6, "bytes=") != 0) return false;
  std::string spec = r.substr(6);
  if (spec.find(',') != std::string::npos) return false;
  size_t dash = spec.find('-');
  if (dash == std::string::npos) return false;
  std::string a = spec.substr(0, dash), b = spec.substr(dash + 1);
  if (a.empty() && b.empty()) return false;
  if ((!a.empty() && !all_digits(a)) || (!b.empty() && !all_digits(b)))
    return false;  // malformed bounds -> not parseable (Python: 416)
  if (a.empty()) {  // suffix: last n bytes
    int64_t n = strtoll(b.c_str(), nullptr, 10);
    if (n <= 0) return false;
    if (n > total) n = total;
    *start = total - n;
    *length = n;
    return true;
  }
  int64_t s = strtoll(a.c_str(), nullptr, 10);
  if (s >= total) return false;
  int64_t e = b.empty() ? total - 1 : strtoll(b.c_str(), nullptr, 10);
  if (e >= total) e = total - 1;
  if (e < s) return false;
  *start = s;
  *length = e - s + 1;
  return true;
}

void quote_escape(const std::string& in, std::string* out) {
  for (char c : in) {
    if (c == '\\' || c == '"') out->push_back('\\');
    out->push_back(c);
  }
}

void serve_needle(Server* s, int fd, const Request& req, uint32_t vid,
                  uint64_t key, uint32_t cookie) {
  auto vol = s->find(vid);
  if (!vol) {
    redirect_to_fallback(s, fd, req);  // EC / remote / replica logic
    return;
  }
  uint64_t offset;
  uint32_t size;
  {
    std::shared_lock<std::shared_mutex> l(vol->mu);
    auto it = vol->index.find(key);
    if (it == vol->index.end() || it->second.first == 0 ||
        it->second.second == kTombstoneSize) {
      // The index here is only a MIRROR: during a re-sync window
      // (compaction commit, volume copy, tail receive) or after a
      // put/delete reorder it can transiently miss live needles. A
      // miss therefore redirects to the authoritative Python server —
      // a true miss still ends as its 404, a windowed miss is served.
      l.unlock();
      redirect_to_fallback(s, fd, req);
      return;
    }
    offset = it->second.first;
    size = it->second.second;
  }
  int64_t want = actual_size(size, vol->version);
  if (want > s->max_fastpath_bytes) {  // huge blob: let Python stream it
    redirect_to_fallback(s, fd, req);
    return;
  }
  std::vector<uint8_t> blob(static_cast<size_t>(want));
  ssize_t got = pread(vol->fd, blob.data(), blob.size(),
                      static_cast<off_t>(offset));
  if (got < want) {
    s->errors++;
    respond_simple(fd, 500, "Internal Server Error", "short read",
                   req.keepalive);
    return;
  }
  ParsedNeedle n;
  if (parse_needle(blob.data(), blob.size(), vol->version, &n) != 0 ||
      n.size != size) {
    s->errors++;
    respond_simple(fd, 500, "Internal Server Error", "corrupt needle",
                   req.keepalive);
    return;
  }
  if (n.cookie != cookie) {
    respond_simple(fd, 404, "Not Found", "cookie mismatch", req.keepalive);
    return;
  }
  if (size > 0 && masked_crc(crc32c(n.data, n.data_size)) != n.checksum) {
    s->errors++;
    respond_simple(fd, 500, "Internal Server Error", "crc mismatch",
                   req.keepalive);
    return;
  }
  // TTL expiry (volume.read_needle)
  if ((n.flags & kFlagHasTtl) && (n.flags & kFlagHasLastModified)) {
    int64_t mins = ttl_minutes(n.ttl_count, n.ttl_unit);
    if (mins > 0 &&
        time(nullptr) - n.last_modified > mins * 60) {
      respond_simple(fd, 404, "Not Found", "needle expired", req.keepalive);
      return;
    }
  }
  // semantics beyond the fast path live in Python
  if (n.flags & (kFlagGzip | kFlagChunkManifest | kFlagHasPairs)) {
    redirect_to_fallback(s, fd, req);
    return;
  }
  char etag[16];
  snprintf(etag, sizeof etag, "%02x%02x%02x%02x", n.checksum >> 24 & 0xFF,
           n.checksum >> 16 & 0xFF, n.checksum >> 8 & 0xFF,
           n.checksum & 0xFF);
  // Last-Modified + If-Modified-Since, checked before the etag
  // (reference volume_server_handlers_read.go:99-109)
  std::string lm_header;
  if ((n.flags & kFlagHasLastModified) && n.last_modified > 0) {
    char buf[64];
    time_t t = static_cast<time_t>(n.last_modified);
    struct tm tmv;
    gmtime_r(&t, &tmv);
    strftime(buf, sizeof buf, "%a, %d %b %Y %H:%M:%S GMT", &tmv);
    lm_header = buf;
    if (!req.if_modified_since.empty()) {
      struct tm ims{};
      if (strptime(req.if_modified_since.c_str(),
                   "%a, %d %b %Y %H:%M:%S GMT", &ims) != nullptr) {
        if (timegm(&ims) >= n.last_modified) {
          std::string hdr = "Last-Modified: " + lm_header +
                            "\r\nEtag: \"" + etag + "\"\r\n";
          respond_simple(fd, 304, "Not Modified", "", req.keepalive, hdr,
                         "application/octet-stream");
          s->served++;
          return;
        }
      }
    }
  }
  // conditional GET (RFC7232 comma list, weak validators, "*")
  if (!req.if_none_match.empty()) {
    std::string quoted = std::string("\"") + etag + "\"";
    std::string inm = req.if_none_match;
    bool match = false;
    size_t pos = 0;
    while (pos <= inm.size()) {
      size_t comma = inm.find(',', pos);
      std::string c = inm.substr(
          pos, comma == std::string::npos ? std::string::npos : comma - pos);
      // trim + strip weak prefix
      size_t b = c.find_first_not_of(" \t");
      size_t e = c.find_last_not_of(" \t");
      if (b != std::string::npos) {
        c = c.substr(b, e - b + 1);
        if (c.compare(0, 2, "W/") == 0) c = c.substr(2);
        if (c == "*" || c == quoted) {
          match = true;
          break;
        }
      }
      if (comma == std::string::npos) break;
      pos = comma + 1;
    }
    if (match) {
      // header set mirrors the Python 304 (Etag + default octet-stream)
      std::string hdr = "Etag: " + quoted + "\r\n";
      respond_simple(fd, 304, "Not Modified", "", req.keepalive, hdr,
                     "application/octet-stream");
      s->served++;
      return;
    }
  }
  const char* ctype = "application/octet-stream";
  std::string mime_hold;
  if ((n.flags & kFlagHasMime) && !n.mime.empty()) {
    mime_hold = n.mime;
    ctype = mime_hold.c_str();
  }
  // image resize queries never reach here (any '?' redirects), so a
  // plain GET of an image serves stored bytes — same as Python with no
  // width/height args.
  const uint8_t* body = n.data;
  int64_t total = n.data_size;
  int64_t start = 0, length = total;
  bool ranged = false;
  if (!req.range.empty()) {
    if (parse_range_header(req.range, total, &start, &length)) {
      ranged = true;
    } else if (req.range.compare(0, 6, "bytes=") == 0) {
      // unsatisfiable/multi range: Python answers 416 for bad single
      // ranges; multi-ranges fall through to full body there. Redirect
      // so every edge keeps one source of truth.
      redirect_to_fallback(s, fd, req);
      return;
    }
  }
  std::string head;
  head.reserve(512);
  head += ranged ? "HTTP/1.1 206 Partial Content\r\n" : "HTTP/1.1 200 OK\r\n";
  head += "Content-Length: " + std::to_string(length) + "\r\n";
  head += "Content-Type: ";
  head += ctype;
  head += "\r\nEtag: \"";
  head += etag;
  head += "\"\r\nAccept-Ranges: bytes\r\n";
  if (!lm_header.empty())
    head += "Last-Modified: " + lm_header + "\r\n";
  if (n.flags & kFlagHasName) {
    std::string esc;
    quote_escape(n.name, &esc);
    head += "Content-Disposition: inline; filename=\"" + esc + "\"\r\n";
  }
  if (ranged)
    head += "Content-Range: bytes " + std::to_string(start) + "-" +
            std::to_string(start + length - 1) + "/" +
            std::to_string(total) + "\r\n";
  head += req.keepalive ? "Connection: keep-alive\r\n\r\n"
                        : "Connection: close\r\n\r\n";
  if (req.method == "HEAD")
    send_all(fd, head.data(), head.size());
  else
    send_two(fd, head.data(), head.size(), body + start,
             static_cast<size_t>(length));
  s->served++;
}

void handle_conn(Server* s, int fd) {
  struct timeval tv = {30, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  std::string acc;
  while (!s->stop.load(std::memory_order_relaxed)) {
    Request req;
    int r = read_request(fd, &acc, &req);
    if (r <= 0) break;
    if (req.chunked) req.keepalive = false;  // body framing not parsed
    // drain any request body so leftover bytes can't desync the next
    // keep-alive request (redirected POST/PUT carry Content-Length)
    if (req.content_length > 0) {
      int64_t remaining = req.content_length;
      int64_t from_acc =
          std::min<int64_t>(remaining, static_cast<int64_t>(acc.size()));
      acc.erase(0, static_cast<size_t>(from_acc));
      remaining -= from_acc;
      char sink[8192];
      while (remaining > 0) {
        ssize_t got2 = recv(fd, sink,
                            std::min<int64_t>(remaining,
                                              static_cast<int64_t>(
                                                  sizeof sink)),
                            0);
        if (got2 <= 0) {
          req.keepalive = false;
          break;
        }
        remaining -= got2;
      }
    }
    if (req.method == "GET" || req.method == "HEAD") {
      uint32_t vid, cookie;
      uint64_t key;
      if (parse_fid_path(req.target, &vid, &key, &cookie)) {
        serve_needle(s, fd, req, vid, key, cookie);
      } else {
        redirect_to_fallback(s, fd, req);
      }
    } else {
      redirect_to_fallback(s, fd, req);
    }
    if (!req.keepalive) break;
  }
  close(fd);
  s->live--;
}

void accept_loop(Server* s) {
  for (;;) {
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (s->stop.load()) return;
      usleep(10000);  // EMFILE/transient: don't busy-spin a core
      continue;
    }
    if (s->stop.load()) {
      close(fd);
      return;
    }
    if (s->live.load() >= s->max_conns) {
      respond_simple(fd, 503, "Service Unavailable", "too many connections",
                     false);
      close(fd);
      continue;
    }
    s->live++;
    std::thread(handle_conn, s, fd).detach();
  }
}

}  // namespace

extern "C" {

// Returns an opaque handle (nullptr on failure). `fallback` is the
// host:port of the owning Python volume server (redirect target).
void* swhp_start(const char* host, uint16_t port, const char* fallback,
                 int max_conns) {
  auto s = std::make_unique<Server>();
  s->fallback = fallback ? fallback : "";
  if (max_conns > 0) s->max_conns = max_conns;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  addr.sin_addr.s_addr =
      host && *host ? inet_addr(host) : htonl(INADDR_LOOPBACK);
  if (addr.sin_addr.s_addr == INADDR_NONE ||
      bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0 ||
      listen(fd, 256) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  s->listen_fd = fd;
  Server* raw = s.release();
  raw->acceptor = std::thread(accept_loop, raw);
  return raw;
}

uint16_t swhp_port(void* h) { return static_cast<Server*>(h)->port; }

// Registers (or re-registers, e.g. after compaction) a volume. Opens its
// own fd on the .dat; the index starts empty — push entries with
// swhp_put/swhp_put_bulk. Returns 0 ok, -1 open failure.
int swhp_add_volume(void* h, uint32_t vid, const char* dat_path,
                    int version) {
  Server* s = static_cast<Server*>(h);
  int fd = open(dat_path, O_RDONLY);
  if (fd < 0) return -1;
  auto rec = std::make_shared<VolumeRec>();
  rec->fd = fd;
  rec->version = version;
  std::unique_lock<std::shared_mutex> l(s->vols_mu);
  s->vols[vid] = std::move(rec);
  return 0;
}

int swhp_remove_volume(void* h, uint32_t vid) {
  Server* s = static_cast<Server*>(h);
  std::unique_lock<std::shared_mutex> l(s->vols_mu);
  return s->vols.erase(vid) ? 0 : -1;
}

int swhp_put(void* h, uint32_t vid, uint64_t key, uint64_t offset,
             uint32_t size) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol) return -1;
  std::unique_lock<std::shared_mutex> l(vol->mu);
  vol->index[key] = {offset, size};
  return 0;
}

// Bulk load: parallel arrays (numpy-friendly).
int swhp_put_bulk(void* h, uint32_t vid, const uint64_t* keys,
                  const uint64_t* offsets, const uint32_t* sizes,
                  int64_t count) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol) return -1;
  std::unique_lock<std::shared_mutex> l(vol->mu);
  vol->index.reserve(vol->index.size() + static_cast<size_t>(count));
  for (int64_t i = 0; i < count; i++)
    vol->index[keys[i]] = {offsets[i], sizes[i]};
  return 0;
}

int swhp_delete(void* h, uint32_t vid, uint64_t key) {
  Server* s = static_cast<Server*>(h);
  auto vol = s->find(vid);
  if (!vol) return -1;
  std::unique_lock<std::shared_mutex> l(vol->mu);
  vol->index.erase(key);
  return 0;
}

uint64_t swhp_served(void* h) { return static_cast<Server*>(h)->served; }
uint64_t swhp_redirected(void* h) {
  return static_cast<Server*>(h)->redirected;
}

void swhp_stop(void* h) {
  Server* s = static_cast<Server*>(h);
  s->stop = true;
  shutdown(s->listen_fd, SHUT_RDWR);
  close(s->listen_fd);
  if (s->acceptor.joinable()) s->acceptor.join();
  // give in-flight connection threads a beat to observe stop and finish
  for (int i = 0; i < 200 && s->live.load() > 0; i++)
    usleep(10000);
  // Leak s if connections are stuck: a crash on a wedged shutdown is
  // worse than 1KB at process exit.
  if (s->live.load() == 0) delete s;
}

}  // extern "C"
